"""Disaggregated LLM serving: prefill and decode as separate pools.

The continuous-batching engine (llm_engine.py) couples two very
different workloads on one replica: prefill (compute-bound, O(prompt)
FLOPs, bursty) and decode (HBM-bound, steady per-token). Splitting them
— the DistServe/Mooncake shape, and the decoupled generate path LlamaRL
builds on — lets each pool scale on its own signal and keeps long
prefills from stealing decode ticks.

Data path per request (ingress -> decode -> prefill):

1. The ingress hashes the prompt (prefix_cache.prefix_key) and dispatches
   the stream to a DECODE replica with rendezvous affinity on that hash —
   plus the controller's hot-prefix routing table (handle.py), so the
   request lands where its K/V already lives.
2. Prefix-cache HIT: the decode replica splices the resident K/V into a
   free slot (engine.attach_prefilled) — no prefill anywhere, TTFT is
   just the splice + first tick.
3. MISS: the decode replica calls its prefill-pool handle. The prefill
   replica runs length-bucketed prefill and returns the K/V blob as its
   result; pulling that result IS PR 7's streamed raw-tail worker<->worker
   transfer (producer-serves-own-objects, recv_into the destination
   buffer) — bytes move prefill->decode directly, never through the
   ingress or controller. The blob lands in the replica's prefix cache,
   then splices mid-flight into a slot.
4. The ingress relays tokens, counting what it has delivered. If the
   decode replica dies mid-stream it re-dispatches to another replica
   (router refresh + the same affinity hash, so a cached holder is
   preferred; re-prefill otherwise) and SKIPS the tokens already sent —
   greedy decoding replays exactly, so the client sees no duplicate and
   no lost token. Sampled requests (temperature > 0) cannot be resumed
   this way: each replica follows its own sampling trajectory, so a
   mid-stream death after tokens were delivered surfaces as an error
   instead of silently stitching two incompatible generations (a
   sampled stream with NO tokens delivered yet still retries — a fresh
   trajectory is a valid response).

Prefix hashes are derived server-side from the tokens, always: the hash
keys the prefix cache, so trusting a client-supplied ``prefix_hash``
would let one request poison (or read) the cached K/V of another
prompt. The field is stripped from incoming requests.

``RTPU_SERVE_DISAGG=0`` collapses build_disagg_llm_deployment to the
unified single-pool continuous-batching deployment with the identical
request/response contract.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu import flags

from .deployment import deployment
from .llm import build_streaming_llm_deployment
from .prefix_cache import PrefixCache, prefix_key

logger = logging.getLogger(__name__)

_disagg_metrics_cache = None


def _disagg_metrics():
    global _disagg_metrics_cache
    if _disagg_metrics_cache is None:
        from ray_tpu.util.metrics import Counter

        _disagg_metrics_cache = {
            "handoff": Counter(
                "rtpu_serve_handoff_bytes_total",
                description="K/V bytes handed off prefill->decode over "
                            "the streamed worker-to-worker object path",
                tag_keys=("model",)),
            "reroutes": Counter(
                "rtpu_serve_reroutes_total",
                description="Token streams re-dispatched to another "
                            "decode replica after a mid-stream replica "
                            "failure",
                tag_keys=("model",)),
        }
    return _disagg_metrics_cache


def build_disagg_llm_deployment(cfg, params_factory, *, name: str = "llm",
                                num_prefill_replicas: int = 1,
                                num_decode_replicas: int = 1,
                                num_slots: int = 4,
                                max_prompt_len: int = 256,
                                max_new_tokens: int = 64,
                                num_tpus: Optional[int] = None,
                                quantize_int8: bool = False,
                                prefill_scaling_policy: Optional[Dict] = None,
                                decode_scaling_policy: Optional[Dict] = None,
                                prefix_cache_mb: Optional[float] = None):
    """The disaggregated LLM application: returns an Application for
    serve.run whose ingress speaks the same streamed
    {"tokens": [...]} -> {"token": id}* contract as
    build_streaming_llm_deployment (which it degrades to, byte-identical,
    when RTPU_SERVE_DISAGG=0).

    ``*_scaling_policy`` dicts (serve/autoscaler.py ScalingPolicy fields)
    put each pool under the signal-driven autoscaler."""
    if not flags.get("RTPU_SERVE_DISAGG"):
        return build_streaming_llm_deployment(
            cfg, params_factory, name=name,
            max_prompt_len=max_prompt_len,
            max_new_tokens=max_new_tokens,
            num_replicas=num_decode_replicas, num_tpus=num_tpus,
            quantize_int8=quantize_int8, continuous_batching=True,
            num_slots=num_slots).bind()

    actor_opts = {"num_tpus": num_tpus} if num_tpus else None

    @deployment(name=f"{name}-prefill",
                num_replicas=num_prefill_replicas,
                ray_actor_options=actor_opts, pool="prefill",
                scaling_policy=prefill_scaling_policy)
    class PrefillWorker:
        """Length-bucketed prefill; returns the handoff blob as its call
        result — the decode replica's pull of that result is the
        streamed worker<->worker transfer."""

        def __init__(self):
            import threading

            import jax

            from ray_tpu.models.generate import prefill

            self._params = params_factory()
            if quantize_int8:
                from ray_tpu.models.quantize import quantize_params_int8

                self._params = quantize_params_int8(self._params)

            def _pf(params, tokens, length):
                logits, cache = prefill(params, tokens, cfg,
                                        tokens.shape[1], lengths=length)
                return logits[0], cache.k[:, 0], cache.v[:, 0]

            self._prefill = jax.jit(_pf)
            self._lock = threading.Lock()
            self._inflight = 0

        def prefill(self, tokens) -> Dict[str, Any]:
            import jax.numpy as jnp

            from ray_tpu.serve import trace
            from ray_tpu.serve.llm_engine import bucket_len

            with self._lock:
                self._inflight += 1
            # Prefill-execution span on the PREFILL replica's own clock:
            # the nested handle call carried the trace over, so this
            # lands in the same waterfall as the decode-side hops.
            hop = trace.start_hop("serve.prefill", kind="prefill",
                                  attributes={"model": name})
            try:
                ids = np.asarray(tokens, np.int32)
                if ids.ndim != 1 or ids.size == 0:
                    raise ValueError("tokens must be a non-empty 1-D "
                                     "integer list")
                ids = ids[-max_prompt_len:]
                S = bucket_len(len(ids), max_prompt_len)
                padded = np.zeros((1, S), np.int32)
                padded[0, :len(ids)] = ids
                logits, k, v = self._prefill(
                    self._params, jnp.asarray(padded),
                    jnp.asarray([len(ids)], jnp.int32))
                if hop is not None:
                    hop.attributes.update(prompt_len=len(ids), bucket=S)
                return {"k": np.asarray(k), "v": np.asarray(v),
                        "length": len(ids), "logits": np.asarray(logits)}
            except BaseException as e:
                if hop is not None:
                    hop.end(error=type(e).__name__)
                    hop = None
                raise
            finally:
                if hop is not None:
                    hop.end()
                with self._lock:
                    self._inflight -= 1

        def __call__(self, tokens) -> Dict[str, Any]:
            return self.prefill(tokens)

        def serve_stats(self) -> Dict[str, float]:
            n = self._inflight
            return {"queued": float(max(0, n - 1)),
                    "slots_busy": float(min(n, 1)),
                    "slots_total": 1.0,
                    "occupancy": float(min(n, 1))}

    @deployment(name=f"{name}-decode", num_replicas=num_decode_replicas,
                # Well above num_slots: excess streams block INSIDE the
                # engine's slot wait (where they register as queue depth —
                # the autoscaler's primary signal) instead of saturating
                # the actor mailbox, which would starve the controller's
                # stats/health probes exactly when the pool is overloaded.
                max_ongoing_requests=max(64, 4 * num_slots), stream=True,
                ray_actor_options=actor_opts, pool="decode",
                scaling_policy=decode_scaling_policy)
    class DecodeWorker:
        """Continuous-batching decode replica with a resident prefix
        cache; prefill comes from the cache, the prefill pool, or (last
        resort) locally."""

        def __init__(self, prefill_handle=None):
            import os
            import threading

            from ray_tpu.serve.llm_engine import ContinuousBatchingEngine

            self._params = params_factory()
            if quantize_int8:
                from ray_tpu.models.quantize import quantize_params_int8

                self._params = quantize_params_int8(self._params)
            self._engine = ContinuousBatchingEngine(
                cfg, self._params, num_slots=num_slots,
                max_prompt_len=max_prompt_len,
                max_new_tokens=max_new_tokens,
                seed=int.from_bytes(os.urandom(4), "little"), model=name)
            self._prefill_pool = prefill_handle
            mb = prefix_cache_mb
            self._cache = PrefixCache(
                max_bytes=None if mb is None else int(mb * 2**20),
                model=name)
            self._mtags = {"model": name}
            self._stop = threading.Event()
            self._ticker = threading.Thread(
                target=self._engine.run_forever, args=(self._stop,),
                daemon=True)
            self._ticker.start()

        # ------------------------------------------------------ the stream

        def _obtain_prefill(self, h: str, ids: np.ndarray,
                            timeout: Optional[float]):
            """(k, v, length, logits) for this prompt: cache hit ->
            resident blob; miss -> prefill pool (streamed handoff pull);
            pool failure -> local prefill fallback."""
            from ray_tpu.serve import trace

            e = self._cache.get(h)
            if e is not None:
                return e.k, e.v, e.length, e.logits
            blob = None
            if self._prefill_pool is not None:
                # KV-handoff span: the prefill-pool RPC + result pull —
                # its dwell IS the transfer time, bytes attached below.
                hop = trace.start_hop("serve.kv_handoff", kind="handoff",
                                      attributes={"model": name})
                try:
                    blob = self._prefill_pool.prefill.remote(
                        [int(t) for t in ids]).result(timeout=timeout)
                    nbytes = float(blob["k"].nbytes + blob["v"].nbytes
                                   + blob["logits"].nbytes)
                    _disagg_metrics()["handoff"].inc(
                        nbytes, tags=self._mtags)
                    if hop is not None:
                        hop.end(bytes=int(nbytes))
                        hop = None
                except Exception as exc:
                    if hop is not None:
                        hop.end(error=type(exc).__name__)
                        hop = None
                    logger.warning(
                        "prefill pool unavailable (%s); falling back to "
                        "local prefill", exc)
                    blob = None
            if blob is None:
                hop = trace.start_hop("serve.prefill", kind="prefill",
                                      attributes={"model": name,
                                                  "local": True})
                try:
                    k, v, length, logits = self._engine.prefill_only(ids)
                except BaseException as exc:
                    if hop is not None:
                        hop.end(error=type(exc).__name__)
                    raise
                if hop is not None:
                    hop.end()
                blob = {"k": k, "v": v, "length": length,
                        "logits": logits}
            self._cache.put(h, blob["k"], blob["v"], blob["length"],
                            blob["logits"])
            return (blob["k"], blob["v"], blob["length"], blob["logits"])

        def __call__(self, request: Dict[str, Any]):
            from ray_tpu.serve import context as serve_context
            from ray_tpu.serve import trace

            try:
                ids = np.asarray(request["tokens"], np.int32)
                if ids.ndim != 1 or ids.size == 0:
                    raise ValueError("tokens must be a non-empty 1-D "
                                     "integer list")
                n = int(request.get("max_new_tokens", max_new_tokens))
                if n <= 0:
                    raise ValueError("max_new_tokens must be positive")
                n = min(n, max_new_tokens)
                temp = float(request.get("temperature", 0.0))
                eos = request.get("eos_id")
                eos = None if eos is None else int(eos)
            except Exception as e:
                yield {"error": f"bad request: {e}"}
                return
            ids = ids[-max_prompt_len:]
            # Always derived from the tokens, never read from the request:
            # a forged hash would poison the cache entry for another
            # prompt (or serve that prompt's cached K/V and logits here).
            h = prefix_key(ids)
            timeout = serve_context.remaining_s(default=300.0)
            # The stream span covers prefill-obtain -> attach -> last
            # token on THIS replica; its end attaches the engine's token
            # stats (computed BEFORE abort, which would drop the ring).
            hop = trace.start_hop(
                "serve.stream", kind="decode",
                attributes={"model": name,
                            "prefix_hit": h in self._cache})
            req = None
            sent = 0
            status = "ok"
            try:
                try:
                    k, v, length, logits = self._obtain_prefill(h, ids,
                                                                timeout)
                    req = self._engine.attach_prefilled(
                        k, v, length, logits, max_new_tokens=n,
                        temperature=temp, eos_id=eos, timeout=timeout,
                        queue_wait_s=serve_context.elapsed_s())
                except TimeoutError as e:
                    status = "slot_timeout"
                    yield {"error": f"overloaded: {e}"}
                    return
                while True:
                    if serve_context.expired():
                        from ray_tpu.core.controller import (
                            DeadlineExceededError,
                        )

                        status = "deadline"
                        raise DeadlineExceededError(
                            "request deadline passed mid-stream")
                    toks = self._engine.peek(req)
                    while sent < len(toks):
                        yield {"token": toks[sent]}
                        sent += 1
                    if self._engine.check_failed() is not None \
                            and not self._engine.is_done(req):
                        status = "engine_failed"
                        yield {"error": "generation engine failed"}
                        return
                    if self._engine.is_done(req):
                        try:
                            tail = self._engine.pop_result(req)[sent:]
                        except RuntimeError as e:
                            status = "engine_failed"
                            yield {"error": str(e)}
                            return
                        for tok in tail:
                            yield {"token": tok}
                            sent += 1
                        return
                    time.sleep(0.005)
            except BaseException as e:
                if status == "ok":
                    status = ("cancelled"
                              if isinstance(e, GeneratorExit)
                              else type(e).__name__)
                raise
            finally:
                st = (self._engine.token_stats(req) or {}) \
                    if req is not None else {}
                if req is not None:
                    self._engine.abort(req)
                if hop is not None:
                    attrs: Dict[str, Any] = {"sent": sent,
                                             "status": status}
                    for k_, v_ in st.items():
                        if v_ is not None:
                            attrs[k_] = (round(v_, 6)
                                         if isinstance(v_, float) else v_)
                    hop.end(**attrs)

        # -------------------------------------------------- prefix plane

        def has_prefix(self, h: str) -> bool:
            return h in self._cache

        def export_prefix(self, h: str) -> Optional[Dict[str, Any]]:
            return self._cache.export(h)

        def pull_prefix(self, h: str, holder) -> bool:
            """Promotion pull: fetch a cluster-hot blob straight from the
            holder replica actor (controller only brokers WHO, the bytes
            stream holder->here)."""
            if not self._cache.enabled or h in self._cache:
                return True
            try:
                blob = ray_tpu.get(
                    holder.handle_request.remote("export_prefix", (h,),
                                                 {}),
                    timeout=30.0)
            except Exception:
                return False
            if not blob:
                return False
            return self._cache.insert_blob(h, blob)

        def cache_stats(self) -> Dict[str, Any]:
            return self._cache.stats()

        def pid(self) -> int:
            import os

            return os.getpid()

        def serve_stats(self) -> Dict[str, Any]:
            out: Dict[str, Any] = self._engine.stats()
            out["prefix"] = self._cache.stats()
            return out

        def __del__(self):
            try:
                self._stop.set()
            except Exception:
                pass

    @deployment(name=name, stream=True, max_ongoing_requests=64)
    class DisaggIngress:
        """Routes streams to the decode pool with prefix affinity and
        replays across decode-replica death without duplicating or
        losing tokens (exact replay needs greedy decoding; a sampled
        stream that already delivered tokens fails over to an error)."""

        def __init__(self, decode_handle):
            self._decode = decode_handle
            self._mtags = {"model": name}

        def __call__(self, request: Dict[str, Any]):
            from ray_tpu.core.controller import DeadlineExceededError

            from .admission import BackPressureError
            from .trace import start_hop

            if not isinstance(request, dict) or "tokens" not in request:
                yield {"error": "expected {'tokens': [...]} request body"}
                return
            try:
                ids = np.asarray(request["tokens"],
                                 np.int32)[-max_prompt_len:]
                # Server-derived affinity/cache key; any client-supplied
                # prefix_hash is dropped (cache-poisoning vector).
                h = prefix_key(ids)
                greedy = float(request.get("temperature", 0.0) or 0.0) <= 0.0
            except Exception as e:
                yield {"error": f"bad request: {e}"}
                return
            request = dict(request)
            request.pop("prefix_hash", None)
            retries = int(flags.get("RTPU_SERVE_DISAGG_RETRIES"))
            sent = 0
            attempt = 0
            while True:
                stream = None
                # One span PER ATTEMPT, all on the ingress replica's
                # clock and sharing the request's trace_id: when a decode
                # replica dies mid-stream and its unshipped spans die with
                # it, the ledger row still links every attempt.
                attempt_hop = start_hop(
                    "serve.decode_attempt", kind="ingress",
                    attributes={"model": name, "attempt": attempt + 1,
                                "skip": sent})
                try:
                    stream = self._decode.options(
                        stream=True,
                        multiplexed_model_id=h).remote(request)
                    skip = sent
                    for chunk in stream:
                        if isinstance(chunk, dict) and "error" in chunk:
                            if "engine failed" in str(chunk["error"]):
                                # Sick replica: retryable elsewhere.
                                raise RuntimeError(chunk["error"])
                            yield chunk
                            return
                        if skip:
                            # Replayed prefix of a re-dispatched stream:
                            # the client already has these tokens.
                            skip -= 1
                            continue
                        sent += 1
                        yield chunk
                    return
                except (BackPressureError, DeadlineExceededError):
                    raise
                except Exception as e:
                    if attempt_hop is not None:
                        attempt_hop.end(error=type(e).__name__,
                                        sent=sent)
                        attempt_hop = None
                    if sent and not greedy:
                        # Sampled streams don't replay: another replica
                        # follows a different trajectory, so skipping
                        # `sent` tokens would stitch two incompatible
                        # generations. Surface the failure instead.
                        yield {"error": "decode replica died mid-stream; "
                                        "sampled (temperature > 0) "
                                        "streams cannot be resumed: "
                                        f"{e}"}
                        return
                    attempt += 1
                    if attempt > retries:
                        yield {"error": f"decode stream failed after "
                                        f"{attempt} attempts: {e}"}
                        return
                    _disagg_metrics()["reroutes"].inc(1.0,
                                                      tags=self._mtags)
                    logger.warning(
                        "decode stream for %s died (%s); re-routing "
                        "(attempt %d, %d tokens already delivered)",
                        name, e, attempt, sent)
                    try:
                        self._decode._ensure_router()._refresh(force=True)
                    except Exception:
                        pass
                    time.sleep(min(0.25 * attempt, 1.0))
                finally:
                    if attempt_hop is not None:
                        attempt_hop.end(sent=sent)
                    if stream is not None:
                        try:
                            stream.close()
                        except Exception:
                            pass

    return DisaggIngress.bind(DecodeWorker.bind(PrefillWorker.bind()))
