"""Serve: model serving on the actor runtime.

Parity map (reference python/ray/serve/, SURVEY.md §2.6):
- @serve.deployment / .bind() graph     -> deployment.py
- ServeController + DeploymentState     -> controller.py
- ReplicaActor + UserCallableWrapper    -> replica.py
- DeploymentHandle + pow-2 Router       -> handle.py
- HTTP proxy (ASGI)                     -> proxy.py
- @serve.batch                          -> batching.py
- serve.run/start/delete/status         -> api.py
- LLM deployment over models.generate    -> llm.py
"""
from ray_tpu.core.controller import DeadlineExceededError

from .admission import BackPressureError
from .api import (delete, get_app_handle, get_deployment_handle, run,
                  shutdown, start, status)
from .batching import batch
from .context import get_request_context, remaining_s
from .multiplex import get_multiplexed_model_id, multiplexed
from .deployment import Application, AutoscalingConfig, Deployment, deployment
from .llm import build_llm_deployment, build_streaming_llm_deployment
from .llm_engine import ContinuousBatchingEngine
from .disagg import build_disagg_llm_deployment
from .prefix_cache import PrefixCache, prefix_key
from .autoscaler import ScalingPolicy
from .handle import (DeploymentHandle, DeploymentResponse,
                     DeploymentStreamingResponse)

__all__ = [
    "BackPressureError",
    "DeadlineExceededError",
    "get_request_context",
    "remaining_s",
    "deployment",
    "Deployment",
    "Application",
    "AutoscalingConfig",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentStreamingResponse",
    "get_multiplexed_model_id",
    "multiplexed",
    "run",
    "start",
    "shutdown",
    "delete",
    "status",
    "get_app_handle",
    "get_deployment_handle",
    "batch",
    "build_llm_deployment",
    "build_streaming_llm_deployment",
    "build_disagg_llm_deployment",
    "ContinuousBatchingEngine",
    "PrefixCache",
    "prefix_key",
    "ScalingPolicy",
]
