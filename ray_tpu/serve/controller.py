"""ServeController: the control-plane actor.

Parity: reference serve/_private/controller.py:86 (ServeController) +
deployment_state.py:1226 (DeploymentState reconciliation): holds target
state per deployment, reconciles actual replica actors toward it, restarts
dead replicas, runs queue-metric autoscaling
(autoscaling_state.py:82 / replica_queue_length_autoscaling_policy), and
answers routing queries (replica handle lists, versioned so routers can
long-poll-style refresh cheaply).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

import ray_tpu
from ray_tpu import flags

from .autoscaler import ServeAutoscaler
from .prefix_cache import PrefixIndex
from .replica import ReplicaActor

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"

_plane_metrics_cache = None


def _plane_metrics():
    """Controller-exported serve-plane gauges: the autoscaler's inputs and
    `rtpu top`'s SERVE section read these off the shared metrics plane."""
    global _plane_metrics_cache
    if _plane_metrics_cache is None:
        from ray_tpu.util.metrics import Gauge

        _plane_metrics_cache = {
            "queue": Gauge(
                "rtpu_serve_queue_depth",
                description="Requests queued for a generation slot across "
                            "a deployment's replicas (serve controller "
                            "stats poll)",
                tag_keys=("model",)),
            "replicas": Gauge(
                "rtpu_serve_replicas",
                description="Live replica count per serve deployment "
                            "(pool label: prefill | decode | main)",
                tag_keys=("deployment", "pool")),
            "occupancy": Gauge(
                "rtpu_serve_slot_occupancy",
                description="Continuous-batching slot occupancy in [0,1] "
                            "across a deployment's replicas",
                tag_keys=("model",)),
        }
    return _plane_metrics_cache


class _DeploymentInfo:
    def __init__(self, name: str, serialized_callable: bytes, init_args,
                 init_kwargs, config: Dict[str, Any]):
        self.name = name
        self.serialized_callable = serialized_callable
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config = config
        self.target_replicas: int = config["num_replicas"]
        self.replicas: List[Any] = []  # ActorHandles
        # Scale-down victims mid-drain: (handle, drain_start_ts). Out of
        # the routed set (version bump) but alive until idle or the drain
        # deadline — in-flight streams finish across a resize.
        self.draining: List[Tuple[Any, float]] = []
        self.version = 0
        self.last_error: Optional[str] = None
        # Latest aggregated serving signals from the stats poll.
        self.signals: Dict[str, float] = {}
        # autoscaling bookkeeping: when the metric FIRST crossed the
        # threshold (None = currently below it) — delays require sustained
        # load, not merely time-since-last-event.
        self.above_since: Optional[float] = None
        self.below_since: Optional[float] = None


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, _DeploymentInfo] = {}
        self._route_prefixes: Dict[str, str] = {}  # prefix -> deployment
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # Signal-driven pool scaling + cluster prefix index (per
        # deployment), both fed by the per-tick replica stats poll.
        self._autoscaler = ServeAutoscaler()
        self._prefix_index: Dict[str, PrefixIndex] = {}
        self._loop = threading.Thread(target=self._control_loop, daemon=True)
        self._loop.start()

    # ------------------------------------------------------------ deploy API

    def deploy(self, name: str, serialized_callable: bytes, init_args,
               init_kwargs, config: Dict[str, Any],
               route_prefix: Optional[str] = None) -> None:
        with self._lock:
            info = self._deployments.get(name)
            if info is None:
                info = _DeploymentInfo(name, serialized_callable, init_args,
                                       init_kwargs, config)
                self._deployments[name] = info
            else:
                info.serialized_callable = serialized_callable
                info.init_args = init_args
                info.init_kwargs = init_kwargs
                info.config = config
                info.target_replicas = config["num_replicas"]
                # In-place redeploy: drop old replicas; reconcile restarts.
                for r in info.replicas:
                    self._kill_replica(r)
                info.replicas = []
                info.version += 1
                self._publish_update(name, info.version)
            if route_prefix:
                self._route_prefixes[route_prefix] = name
            self._autoscaler.configure(name, config.get("scaling_policy"))
        self._reconcile()

    def _publish_update(self, name: str, version: int) -> None:
        """Push-based config propagation (reference: serve LongPollHost
        notifying handles on replica-set changes, long_poll.py:173) — the
        core pubsub replaces per-call version polling in routers."""
        try:
            from ray_tpu.core import context as ctx

            ctx.get_worker_context().client.request(
                {"kind": "publish", "channel": "serve_updates",
                 "data": {"name": name, "version": version}})
        except Exception:
            pass  # routers still have the periodic refresh as backstop

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            info = self._deployments.pop(name, None)
            self._route_prefixes = {
                p: d for p, d in self._route_prefixes.items() if d != name}
            self._autoscaler.forget(name)
            self._prefix_index.pop(name, None)
        if info:
            for r in info.replicas:
                self._kill_replica(r)
            for r, _ in info.draining:
                self._kill_replica(r)

    def shutdown(self) -> None:
        self._stop.set()
        with self._lock:
            names = list(self._deployments)
        for n in names:
            self.delete_deployment(n)

    # -------------------------------------------------------------- routing

    def get_replicas(self, name: str) -> Tuple[int, List[Any]]:
        """(version, replica handles) — routers cache until version bumps."""
        info = self._deployments.get(name)
        if info is None:
            raise KeyError(f"no deployment {name!r}")
        return info.version, list(info.replicas)

    def get_routing_config(self, name: str) -> Dict[str, Any]:
        """Admission-relevant config subset, fetched by routers alongside
        the replica list: replica concurrency bound + queued-request bound
        (None max_queued_requests defers to the RTPU_SERVE_MAX_QUEUED
        flag default; -1 means unbounded)."""
        info = self._deployments.get(name)
        if info is None:
            raise KeyError(f"no deployment {name!r}")
        out = {
            "max_ongoing_requests": int(
                info.config.get("max_ongoing_requests", 16) or 16),
            "max_queued_requests": info.config.get("max_queued_requests"),
        }
        idx = self._prefix_index.get(name)
        if idx is not None:
            # Hot-prefix steering table: hash -> holder replica ids, so
            # routers send a request where its K/V already lives.
            out["prefix_routes"] = idx.routes()
        return out

    def get_deployment_names(self) -> List[str]:
        return list(self._deployments)

    def get_route_table(self) -> Dict[str, str]:
        return dict(self._route_prefixes)

    def get_route_info(self) -> Dict[str, Dict[str, Any]]:
        """Route table with per-deployment metadata the proxy needs (stream
        flag for chunked responses)."""
        out: Dict[str, Dict[str, Any]] = {}
        for prefix, name in self._route_prefixes.items():
            info = self._deployments.get(name)
            out[prefix] = {
                "name": name,
                "stream": bool(info and info.config.get("stream")),
            }
        return out

    def get_last_error(self, name: str) -> Optional[str]:
        info = self._deployments.get(name)
        return info.last_error if info else None

    # ---------------------------------------------------------- reconcile

    def _make_replica(self, info: _DeploymentInfo):
        opts = dict(info.config.get("ray_actor_options") or {})
        opts.setdefault("num_cpus", 0.1)
        # Replicas serve concurrently up to max_ongoing_requests (mailbox
        # thread pool) — required for @serve.batch to ever see a batch.
        opts.setdefault("max_concurrency",
                        info.config.get("max_ongoing_requests", 16))
        cls = ray_tpu.remote(ReplicaActor).options(**opts)
        return cls.remote(info.serialized_callable, info.init_args,
                          info.init_kwargs, info.config.get("user_config"))

    def _kill_replica(self, handle) -> None:
        try:
            ray_tpu.get(handle.prepare_shutdown.remote(), timeout=2.0)
        except Exception:
            pass
        try:
            ray_tpu.kill(handle)
        except Exception:
            pass

    def _reconcile(self) -> None:
        # Snapshot under _lock, health-check OUTSIDE it (hung replicas cost
        # up to the 30s health window; holding the lock through that would
        # stall every deploy/delete), then re-acquire and commit only if the
        # deployment wasn't concurrently redeployed — otherwise a stale pass
        # could resurrect just-killed old-version replicas.
        with self._lock:
            snapshot = [(info, list(info.replicas)) for info in
                        self._deployments.values()]
        # ONE deadline for the whole pass (probes are fired concurrently
        # per deployment): hung replicas across many deployments must not
        # stack 30s each before replacements start.
        deadline = time.monotonic() + 30.0
        for info, replicas in snapshot:
            alive = []
            dead = []
            # Fire every probe first, then gather against the shared pass
            # deadline (30s — the reference serve default,
            # health_check_timeout_s=30: a replica blocking its loop on a
            # long model compile/load must not read as dead). Serial waits
            # would stall a pass 30s PER hung replica.
            probes = []
            for r in replicas:
                try:
                    probes.append((r, r.check_health.remote()))
                except Exception as e:
                    info.last_error = repr(e)
                    dead.append(r)
            for r, ref in probes:
                try:
                    ray_tpu.get(ref, timeout=max(
                        0.5, deadline - time.monotonic()))
                    alive.append(r)
                except Exception as e:
                    logger.warning("replica of %s failed health check",
                                   info.name)
                    info.last_error = repr(e)
                    dead.append(r)
            with self._lock:
                if (self._deployments.get(info.name) is not info
                        or info.replicas != replicas):
                    continue  # redeployed/deleted meanwhile: skip this pass
                for r in dead:
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass
                changed = len(alive) != len(replicas)
                while len(alive) < info.target_replicas:
                    alive.append(self._make_replica(info))
                    changed = True
                now = time.time()
                while len(alive) > info.target_replicas:
                    # Scale-down DRAINS instead of killing: the victim
                    # leaves the routed set on this version bump (routers
                    # stop picking it) and _reap_draining() kills it only
                    # once idle or past RTPU_SERVE_DRAIN_DEADLINE_S — a
                    # resize never cuts an in-flight stream.
                    info.draining.append((alive.pop(), now))
                    changed = True
                if changed:
                    info.replicas = alive
                    info.version += 1
                    self._publish_update(info.name, info.version)

    def _reap_draining(self) -> None:
        """Kill draining replicas that went idle (or overstayed the drain
        deadline). Probes run OUTSIDE the lock — a hung drain victim must
        not stall deploys."""
        with self._lock:
            snapshot = [(info, list(info.draining))
                        for info in self._deployments.values()
                        if info.draining]
        if not snapshot:
            return
        grace = flags.get("RTPU_SERVE_DRAIN_DEADLINE_S")
        now = time.time()
        for info, entries in snapshot:
            reaped = []
            for r, ts in entries:
                kill = now - ts >= grace
                if not kill:
                    try:
                        kill = ray_tpu.get(r.queue_len.remote(),
                                           timeout=2.0) == 0
                    except (ray_tpu.ActorDiedError,
                            ray_tpu.WorkerCrashedError):
                        kill = True  # actually dead: nothing to drain
                    except Exception:
                        # Probe timed out / transient failure: a LIVE
                        # replica can be briefly unresponsive (JIT
                        # compile holding the GIL, busy engine tick).
                        # Killing it now would cut in-flight streams —
                        # keep draining; the grace deadline decides.
                        kill = False
                if kill:
                    self._kill_replica(r)
                    reaped.append(r)
            if reaped:
                with self._lock:
                    info.draining = [(r, ts) for r, ts in info.draining
                                     if r not in reaped]

    # ------------------------------------------------------- signal plane

    def _poll_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-deployment serving signals from replica stats(): queue
        depth (blocked submitters), slot occupancy, prefix-cache holdings
        (folded into the cluster PrefixIndex). Also exports the
        controller-side gauges the autoscaler and `rtpu top` read."""
        with self._lock:
            snapshot = [(info, list(info.replicas))
                        for info in self._deployments.values()]
        signals: Dict[str, Dict[str, float]] = {}
        try:
            m = _plane_metrics()
        except Exception:
            m = None
        for info, replicas in snapshot:
            refs = []
            for r in replicas:
                try:
                    refs.append((r, r.stats.remote()))
                except Exception:
                    pass
            deadline = time.monotonic() + 2.0
            polled = []
            saturated = 0
            for r, ref in refs:
                try:
                    polled.append((r._actor_id, ray_tpu.get(
                        ref, timeout=max(0.1,
                                         deadline - time.monotonic()))))
                except ray_tpu.GetTimeoutError:
                    # The replica is alive but its mailbox is so full the
                    # stats probe couldn't get a thread — which IS the
                    # overload signal. Count it as fully busy with a
                    # waiting queue rather than dropping it, or the
                    # autoscaler would read peak saturation as idle.
                    saturated += 1
                except Exception:
                    pass
            queue = float(saturated)
            busy = total = float(saturated)
            idx = self._prefix_index.get(info.name)
            for rid, s in polled:
                serve = (s or {}).get("serve") or {}
                queue += float(serve.get("queued", 0.0))
                if serve.get("slots_total"):
                    busy += float(serve.get("slots_busy", 0.0))
                    total += float(serve["slots_total"])
                pref = serve.get("prefix")
                if pref:
                    if idx is None:
                        idx = PrefixIndex()
                        self._prefix_index[info.name] = idx
                    idx.update_replica(rid, pref.get("holders") or [],
                                       pref.get("hot") or {})
            if idx is not None:
                live = {r._actor_id for r in replicas}
                for rid in idx.replica_ids():
                    if rid not in live:
                        idx.drop_replica(rid)
            sig = {"queue_depth": queue,
                   "occupancy": (busy / total) if total else 0.0}
            ttft = self._ttft_p99(info.name)
            if ttft is not None:
                sig["ttft_p99_s"] = ttft
            info.signals = sig
            signals[info.name] = sig
            if m is not None:
                pool = info.config.get("pool") or "main"
                try:
                    m["queue"].set(queue, tags={"model": info.name})
                    m["replicas"].set(float(len(replicas)),
                                      tags={"deployment": info.name,
                                            "pool": pool})
                    if total:
                        m["occupancy"].set(sig["occupancy"],
                                           tags={"model": info.name})
                except Exception:
                    pass
        return signals

    def _ttft_p99(self, name: str) -> Optional[float]:
        """Latest per-model TTFT p99 from the telemetry plane — only
        fetched when the deployment's policy actually triggers on it
        (telemetry may be disabled; the signal is best-effort)."""
        p = self._autoscaler.policy(name)
        if p is None or p.ttft_p99_high_s <= 0:
            return None
        try:
            from ray_tpu.util import state as util_state

            res = util_state.query_metrics(
                name="rtpu_serve_ttft_s", tags={"model": name},
                stat="p99", window_s=30.0)
            for ser in (res or {}).get("series") or []:
                pts = ser.get("points") or []
                if pts:
                    return float(pts[-1][1])
        except Exception:
            pass
        return None

    def _autoscale_signals(self, now: float,
                           signals: Dict[str, Dict[str, float]]) -> None:
        """Apply the signal-driven autoscaler's ±1 steps (clamped to the
        policy's replica range); reconcile realizes them — up through the
        deployment path, down through the drain path."""
        deltas = self._autoscaler.step(now, signals)
        if not deltas:
            return
        with self._lock:
            for name, d in deltas.items():
                info = self._deployments.get(name)
                p = self._autoscaler.policy(name)
                if info is None or p is None:
                    continue
                new = max(p.min_replicas,
                          min(p.max_replicas, info.target_replicas + d))
                if new != info.target_replicas:
                    logger.info("serve autoscaler: %s %d -> %d replicas",
                                name, info.target_replicas, new)
                    info.target_replicas = new

    def _promote_prefixes(self) -> None:
        """Broadcast cluster-hot prefixes: replicas missing one pull the
        blob straight from a holder replica (fire-and-forget; bytes move
        worker<->worker, never through the controller)."""
        if not flags.get("RTPU_PREFIX_CACHE"):
            return
        with self._lock:
            snapshot = [(info, list(info.replicas))
                        for info in self._deployments.values()]
        for info, replicas in snapshot:
            idx = self._prefix_index.get(info.name)
            if idx is None or len(replicas) < 2:
                continue
            by_rid = {r._actor_id: r for r in replicas}
            for h, holder_rid, target_rid in idx.promotions(list(by_rid)):
                holder = by_rid.get(holder_rid)
                target = by_rid.get(target_rid)
                if holder is None or target is None:
                    continue
                try:
                    target.handle_request.remote(
                        "pull_prefix", (h, holder), {})
                except Exception:
                    pass

    def get_serve_stats(self) -> Dict[str, Any]:
        """Per-deployment serving snapshot for `rtpu top` / dashboards:
        replica counts (live/target/draining), pool label, and the latest
        polled signals."""
        out: Dict[str, Any] = {}
        with self._lock:
            for name, info in self._deployments.items():
                d = {"replicas": len(info.replicas),
                     "target": info.target_replicas,
                     "draining": len(info.draining),
                     "pool": info.config.get("pool") or "main"}
                d.update(info.signals or {})
                out[name] = d
        return out

    # --------------------------------------------------------- autoscaling

    def _autoscale(self) -> None:
        # Metric: per-replica EXECUTING requests (queue_len). Backlog queued
        # in the actor mailbox beyond max_concurrency is not visible; it
        # surfaces as sustained max-concurrency execution, which still
        # drives upscale.
        now = time.time()
        with self._lock:
            infos = list(self._deployments.values())
        for info in infos:
            ac = info.config.get("autoscaling_config")
            if not ac:
                continue
            ongoing = 0
            for r in list(info.replicas):
                try:
                    ongoing += ray_tpu.get(r.queue_len.remote(), timeout=5.0)
                except Exception:
                    pass
            n = max(1, len(info.replicas))
            per = ongoing / n
            target = info.target_replicas
            if per > ac["target_ongoing_requests"]:
                info.below_since = None
                if info.above_since is None:
                    info.above_since = now
                if now - info.above_since >= ac["upscale_delay_s"]:
                    target = min(ac["max_replicas"],
                                 info.target_replicas + 1)
                    info.above_since = now  # next step needs a fresh window
            elif per < ac["target_ongoing_requests"] * 0.5:
                info.above_since = None
                if info.below_since is None:
                    info.below_since = now
                if now - info.below_since >= ac["downscale_delay_s"]:
                    target = max(ac["min_replicas"],
                                 info.target_replicas - 1)
                    info.below_since = now
            else:
                info.above_since = None
                info.below_since = None
            info.target_replicas = target

    # ------------------------------------------------------------ the loop

    def _control_loop(self) -> None:
        while not self._stop.is_set():
            try:
                now = time.time()
                signals = self._poll_stats()
                self._autoscale()
                self._autoscale_signals(now, signals)
                self._reconcile()
                self._reap_draining()
                self._promote_prefixes()
            except Exception:
                logger.exception("serve control loop error")
            self._stop.wait(1.0)

    def ping(self) -> str:
        return "pong"
