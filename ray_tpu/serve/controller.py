"""ServeController: the control-plane actor.

Parity: reference serve/_private/controller.py:86 (ServeController) +
deployment_state.py:1226 (DeploymentState reconciliation): holds target
state per deployment, reconciles actual replica actors toward it, restarts
dead replicas, runs queue-metric autoscaling
(autoscaling_state.py:82 / replica_queue_length_autoscaling_policy), and
answers routing queries (replica handle lists, versioned so routers can
long-poll-style refresh cheaply).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

import ray_tpu
from .replica import ReplicaActor

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"


class _DeploymentInfo:
    def __init__(self, name: str, serialized_callable: bytes, init_args,
                 init_kwargs, config: Dict[str, Any]):
        self.name = name
        self.serialized_callable = serialized_callable
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config = config
        self.target_replicas: int = config["num_replicas"]
        self.replicas: List[Any] = []  # ActorHandles
        self.version = 0
        self.last_error: Optional[str] = None
        # autoscaling bookkeeping: when the metric FIRST crossed the
        # threshold (None = currently below it) — delays require sustained
        # load, not merely time-since-last-event.
        self.above_since: Optional[float] = None
        self.below_since: Optional[float] = None


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, _DeploymentInfo] = {}
        self._route_prefixes: Dict[str, str] = {}  # prefix -> deployment
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._loop = threading.Thread(target=self._control_loop, daemon=True)
        self._loop.start()

    # ------------------------------------------------------------ deploy API

    def deploy(self, name: str, serialized_callable: bytes, init_args,
               init_kwargs, config: Dict[str, Any],
               route_prefix: Optional[str] = None) -> None:
        with self._lock:
            info = self._deployments.get(name)
            if info is None:
                info = _DeploymentInfo(name, serialized_callable, init_args,
                                       init_kwargs, config)
                self._deployments[name] = info
            else:
                info.serialized_callable = serialized_callable
                info.init_args = init_args
                info.init_kwargs = init_kwargs
                info.config = config
                info.target_replicas = config["num_replicas"]
                # In-place redeploy: drop old replicas; reconcile restarts.
                for r in info.replicas:
                    self._kill_replica(r)
                info.replicas = []
                info.version += 1
                self._publish_update(name, info.version)
            if route_prefix:
                self._route_prefixes[route_prefix] = name
        self._reconcile()

    def _publish_update(self, name: str, version: int) -> None:
        """Push-based config propagation (reference: serve LongPollHost
        notifying handles on replica-set changes, long_poll.py:173) — the
        core pubsub replaces per-call version polling in routers."""
        try:
            from ray_tpu.core import context as ctx

            ctx.get_worker_context().client.request(
                {"kind": "publish", "channel": "serve_updates",
                 "data": {"name": name, "version": version}})
        except Exception:
            pass  # routers still have the periodic refresh as backstop

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            info = self._deployments.pop(name, None)
            self._route_prefixes = {
                p: d for p, d in self._route_prefixes.items() if d != name}
        if info:
            for r in info.replicas:
                self._kill_replica(r)

    def shutdown(self) -> None:
        self._stop.set()
        with self._lock:
            names = list(self._deployments)
        for n in names:
            self.delete_deployment(n)

    # -------------------------------------------------------------- routing

    def get_replicas(self, name: str) -> Tuple[int, List[Any]]:
        """(version, replica handles) — routers cache until version bumps."""
        info = self._deployments.get(name)
        if info is None:
            raise KeyError(f"no deployment {name!r}")
        return info.version, list(info.replicas)

    def get_routing_config(self, name: str) -> Dict[str, Any]:
        """Admission-relevant config subset, fetched by routers alongside
        the replica list: replica concurrency bound + queued-request bound
        (None max_queued_requests defers to the RTPU_SERVE_MAX_QUEUED
        flag default; -1 means unbounded)."""
        info = self._deployments.get(name)
        if info is None:
            raise KeyError(f"no deployment {name!r}")
        return {
            "max_ongoing_requests": int(
                info.config.get("max_ongoing_requests", 16) or 16),
            "max_queued_requests": info.config.get("max_queued_requests"),
        }

    def get_deployment_names(self) -> List[str]:
        return list(self._deployments)

    def get_route_table(self) -> Dict[str, str]:
        return dict(self._route_prefixes)

    def get_route_info(self) -> Dict[str, Dict[str, Any]]:
        """Route table with per-deployment metadata the proxy needs (stream
        flag for chunked responses)."""
        out: Dict[str, Dict[str, Any]] = {}
        for prefix, name in self._route_prefixes.items():
            info = self._deployments.get(name)
            out[prefix] = {
                "name": name,
                "stream": bool(info and info.config.get("stream")),
            }
        return out

    def get_last_error(self, name: str) -> Optional[str]:
        info = self._deployments.get(name)
        return info.last_error if info else None

    # ---------------------------------------------------------- reconcile

    def _make_replica(self, info: _DeploymentInfo):
        opts = dict(info.config.get("ray_actor_options") or {})
        opts.setdefault("num_cpus", 0.1)
        # Replicas serve concurrently up to max_ongoing_requests (mailbox
        # thread pool) — required for @serve.batch to ever see a batch.
        opts.setdefault("max_concurrency",
                        info.config.get("max_ongoing_requests", 16))
        cls = ray_tpu.remote(ReplicaActor).options(**opts)
        return cls.remote(info.serialized_callable, info.init_args,
                          info.init_kwargs, info.config.get("user_config"))

    def _kill_replica(self, handle) -> None:
        try:
            ray_tpu.get(handle.prepare_shutdown.remote(), timeout=2.0)
        except Exception:
            pass
        try:
            ray_tpu.kill(handle)
        except Exception:
            pass

    def _reconcile(self) -> None:
        # Snapshot under _lock, health-check OUTSIDE it (hung replicas cost
        # up to the 30s health window; holding the lock through that would
        # stall every deploy/delete), then re-acquire and commit only if the
        # deployment wasn't concurrently redeployed — otherwise a stale pass
        # could resurrect just-killed old-version replicas.
        with self._lock:
            snapshot = [(info, list(info.replicas)) for info in
                        self._deployments.values()]
        # ONE deadline for the whole pass (probes are fired concurrently
        # per deployment): hung replicas across many deployments must not
        # stack 30s each before replacements start.
        deadline = time.monotonic() + 30.0
        for info, replicas in snapshot:
            alive = []
            dead = []
            # Fire every probe first, then gather against the shared pass
            # deadline (30s — the reference serve default,
            # health_check_timeout_s=30: a replica blocking its loop on a
            # long model compile/load must not read as dead). Serial waits
            # would stall a pass 30s PER hung replica.
            probes = []
            for r in replicas:
                try:
                    probes.append((r, r.check_health.remote()))
                except Exception as e:
                    info.last_error = repr(e)
                    dead.append(r)
            for r, ref in probes:
                try:
                    ray_tpu.get(ref, timeout=max(
                        0.5, deadline - time.monotonic()))
                    alive.append(r)
                except Exception as e:
                    logger.warning("replica of %s failed health check",
                                   info.name)
                    info.last_error = repr(e)
                    dead.append(r)
            with self._lock:
                if (self._deployments.get(info.name) is not info
                        or info.replicas != replicas):
                    continue  # redeployed/deleted meanwhile: skip this pass
                for r in dead:
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass
                changed = len(alive) != len(replicas)
                while len(alive) < info.target_replicas:
                    alive.append(self._make_replica(info))
                    changed = True
                while len(alive) > info.target_replicas:
                    self._kill_replica(alive.pop())
                    changed = True
                if changed:
                    info.replicas = alive
                    info.version += 1
                    self._publish_update(info.name, info.version)

    # --------------------------------------------------------- autoscaling

    def _autoscale(self) -> None:
        # Metric: per-replica EXECUTING requests (queue_len). Backlog queued
        # in the actor mailbox beyond max_concurrency is not visible; it
        # surfaces as sustained max-concurrency execution, which still
        # drives upscale.
        now = time.time()
        with self._lock:
            infos = list(self._deployments.values())
        for info in infos:
            ac = info.config.get("autoscaling_config")
            if not ac:
                continue
            ongoing = 0
            for r in list(info.replicas):
                try:
                    ongoing += ray_tpu.get(r.queue_len.remote(), timeout=5.0)
                except Exception:
                    pass
            n = max(1, len(info.replicas))
            per = ongoing / n
            target = info.target_replicas
            if per > ac["target_ongoing_requests"]:
                info.below_since = None
                if info.above_since is None:
                    info.above_since = now
                if now - info.above_since >= ac["upscale_delay_s"]:
                    target = min(ac["max_replicas"],
                                 info.target_replicas + 1)
                    info.above_since = now  # next step needs a fresh window
            elif per < ac["target_ongoing_requests"] * 0.5:
                info.above_since = None
                if info.below_since is None:
                    info.below_since = now
                if now - info.below_since >= ac["downscale_delay_s"]:
                    target = max(ac["min_replicas"],
                                 info.target_replicas - 1)
                    info.below_since = now
            else:
                info.above_since = None
                info.below_since = None
            info.target_replicas = target

    # ------------------------------------------------------------ the loop

    def _control_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._autoscale()
                self._reconcile()
            except Exception:
                logger.exception("serve control loop error")
            self._stop.wait(1.0)

    def ping(self) -> str:
        return "pong"
