"""@serve.batch — request coalescing inside a replica.

Parity: reference serve/batching.py (@serve.batch): calls queue until
max_batch_size accumulate or batch_wait_timeout_s elapses, then the wrapped
function runs ONCE on the list of requests and each caller gets its element
back. On TPU replicas this is what turns 128 concurrent 1-item requests
into one MXU-shaped batch.
"""
from __future__ import annotations

import functools
import queue
import threading
import time
from typing import Any, Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self._queue: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit(self, item: Any) -> Any:
        slot: "queue.Queue" = queue.Queue(1)
        self._queue.put((item, slot))
        result = slot.get()
        if isinstance(result, _Err):
            raise result.exc
        return result

    def _loop(self) -> None:
        while True:
            item, slot = self._queue.get()
            batch = [(item, slot)]
            # Coalesce: wait up to timeout_s for more, cap at max size.
            t_end = time.time() + self.timeout_s
            while len(batch) < self.max_batch_size:
                remaining = t_end - time.time()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            items = [b[0] for b in batch]
            try:
                results = self.fn(items)
                if len(results) != len(items):
                    raise ValueError(
                        f"batch fn returned {len(results)} results for "
                        f"{len(items)} inputs")
                for (_, s), r in zip(batch, results):
                    s.put(r)
            except Exception as e:
                for _, s in batch:
                    s.put(_Err(e))


class _Err:
    def __init__(self, exc: BaseException):
        self.exc = exc


# Guards batcher creation: concurrent FIRST calls would otherwise each get a
# private batcher and nothing ever coalesces. Module-level (pickled by
# reference) because a lock captured in the decorator closure would make
# decorated deployment classes uncloudpicklable.
_CREATE_LOCK = threading.Lock()


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator for replica methods taking a list of requests."""

    def wrap(fn):
        state: dict = {}

        @functools.wraps(fn)
        def wrapper(*args):
            # Bound method: args = (self, item); function: (item,)
            if len(args) == 2:
                owner, item = args
                key = id(owner)
                caller = lambda items: fn(owner, items)
            else:
                (item,) = args
                key = None
                caller = fn
            b = state.get(key)
            if b is None:
                # Import-at-call: referencing the module-global lock by name
                # would snapshot the (unpicklable) lock into this closure's
                # globals when cloudpickle ships the deployment by value.
                from ray_tpu.serve.batching import _CREATE_LOCK as lock

                with lock:
                    b = state.get(key)
                    if b is None:
                        b = state[key] = _Batcher(
                            caller, max_batch_size, batch_wait_timeout_s)
            return b.submit(item)

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
