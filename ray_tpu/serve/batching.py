"""@serve.batch — request coalescing inside a replica.

Parity: reference serve/batching.py (@serve.batch): calls queue until
max_batch_size accumulate or batch_wait_timeout_s elapses, then the wrapped
function runs ONCE on the list of requests and each caller gets its element
back. On TPU replicas this is what turns 128 concurrent 1-item requests
into one MXU-shaped batch.
"""
from __future__ import annotations

import functools
import queue
import threading
import time
import weakref
from typing import Any, Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self._queue: "queue.Queue" = queue.Queue()
        # The worker holds only a weakref to this batcher: a bound-method
        # target would keep batcher→fn-closure→owner alive forever, making
        # every batched deployment instance immortal. With the weakref the
        # owner↔batcher cycle is ordinary GC fodder and the thread exits
        # once the batcher is collected.
        self._thread = threading.Thread(
            target=_batcher_loop, args=(weakref.ref(self),), daemon=True)
        self._thread.start()

    def submit(self, item: Any) -> Any:
        # Note: the caller's frame keeps `self` strongly referenced for the
        # duration, so the batcher cannot be collected mid-request.
        # The request deadline (serve context, set by the replica around
        # user code) rides along so the seal step can drop expired items.
        from ray_tpu.serve import context as serve_context
        from ray_tpu.serve import trace

        # Trace plane: the enqueue stamp lets the seal sweep attribute each
        # item's coalescing-queue dwell to its request's trace.
        tinfo = None
        if trace.enabled():
            tctx = trace.current_trace_ctx()
            if tctx is not None:
                tinfo = (tctx, time.monotonic(), time.time())
        slot: "queue.Queue" = queue.Queue(1)
        self._queue.put((item, slot, serve_context.get_request_deadline(),
                         tinfo))
        result = slot.get()
        if isinstance(result, _Err):
            raise result.exc
        return result


def _batcher_loop(ref: "weakref.ref[_Batcher]") -> None:
    while True:
        self = ref()
        if self is None:
            return
        q = self._queue
        timeout_s, max_bs = self.timeout_s, self.max_batch_size
        del self  # hold no strong ref (to batcher OR owner) while blocked
        try:
            entry = q.get(timeout=1.0)
        except queue.Empty:
            continue
        # Deref fn only now: fetching it before the blocking get would root
        # the owner<->batcher cycle through this frame for the whole wait,
        # defeating collection. A submitter's frame holds the batcher
        # strongly for the duration of its request, so ref() cannot die
        # between enqueue and here.
        self = ref()
        if self is None:
            return
        fn = self.fn
        del self
        batch = [entry]
        # Coalesce: wait up to timeout_s for more, cap at max size.
        t_end = time.time() + timeout_s
        while len(batch) < max_bs:
            remaining = t_end - time.time()
            if remaining <= 0:
                break
            try:
                batch.append(q.get(timeout=remaining))
            except queue.Empty:
                break
        # Seal-time expiry sweep: items whose request deadline has already
        # passed get the typed error instead of a seat in the batch — an
        # expired request must never consume TPU batch capacity.
        now = time.time()
        live = []
        for b in batch:
            dl = b[2]
            if dl is not None and now > dl:
                from ray_tpu.core.controller import DeadlineExceededError

                b[1].put(_Err(DeadlineExceededError(
                    "request deadline passed while waiting in batch queue")))
            else:
                live.append(b)
        # Seal spans: each traced item's dwell between its submit and this
        # seal (measured on this host's monotonic clock), expired items
        # flagged — the waterfall's "time lost to coalescing" bar.
        seal_mono = time.monotonic()
        for b in batch:
            t = b[3] if len(b) > 3 else None
            if t is not None:
                from ray_tpu.serve import trace

                trace.emit_span(
                    "serve.batch_seal", trace_ctx=t[0], kind="batch",
                    dwell_s=seal_mono - t[1], start_ts=t[2],
                    attributes={"batch_size": len(live),
                                "expired": b not in live})
        batch = live
        if not batch:
            del fn
            continue
        items = [b[0] for b in batch]
        try:
            results = fn(items)
            if len(results) != len(items):
                raise ValueError(
                    f"batch fn returned {len(results)} results for "
                    f"{len(items)} inputs")
            for b, r in zip(batch, results):
                b[1].put(r)
        except Exception as e:
            for b in batch:
                b[1].put(_Err(e))
        del fn


class _Err:
    def __init__(self, exc: BaseException):
        self.exc = exc


# Guards batcher creation: concurrent FIRST calls would otherwise each get a
# private batcher and nothing ever coalesces. Module-level (pickled by
# reference) because a lock captured in the decorator closure would make
# decorated deployment classes uncloudpicklable.
_CREATE_LOCK = threading.Lock()

# Fallback batcher store for owners with __slots__ (no instance dict):
# weak-keyed so entries die with the instance.
_weak_state: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _weak_get(owner):
    try:
        return _weak_state.get(owner)
    except TypeError:  # not weakref-able
        return None


def _weak_set(owner, batcher) -> bool:
    try:
        _weak_state[owner] = batcher
        return True
    except TypeError:  # not weakref-able
        return False


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator for replica methods taking a list of requests."""

    def wrap(fn):
        state: dict = {}

        # Per-instance batchers live ON the instance (attribute keyed by the
        # wrapped method's name): id(owner) keys can be recycled by CPython
        # after GC, silently routing a new instance's calls to a dead
        # instance's batcher; an instance attribute dies with the instance.
        attr = f"__rtpu_batcher_{fn.__qualname__.replace('.', '_')}"

        @functools.wraps(fn)
        def wrapper(*args):
            # Import-at-call: referencing the module-global lock by name
            # would snapshot the (unpicklable) lock into this closure's
            # globals when cloudpickle ships the deployment by value.
            from ray_tpu.serve.batching import _CREATE_LOCK as lock

            # Bound method: args = (self, item); function: (item,)
            if len(args) == 2:
                owner, item = args
                b = (getattr(owner, attr, None) or _weak_get(owner)
                     or state.get(id(owner)))
                if b is None:
                    with lock:
                        b = (getattr(owner, attr, None) or _weak_get(owner)
                         or state.get(id(owner)))
                        if b is None:
                            b = _Batcher(lambda items: fn(owner, items),
                                         max_batch_size, batch_wait_timeout_s)
                            try:
                                object.__setattr__(owner, attr, b)
                            except (AttributeError, TypeError):
                                # __slots__ owners: key weakly by instance
                                # (dies with it, no id-recycling hazard).
                                # Not even weakref-able: last resort,
                                # id-keyed (leaks only for such owners).
                                if not _weak_set(owner, b):
                                    state[id(owner)] = b
            else:
                (item,) = args
                b = state.get(None)
                if b is None:
                    with lock:
                        b = state.get(None)
                        if b is None:
                            b = state[None] = _Batcher(
                                fn, max_batch_size, batch_wait_timeout_s)
            return b.submit(item)

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
