"""Deployment authoring API.

Parity: reference serve/api.py @serve.deployment + serve/deployment.py
(class Deployment) and the deployment-graph build
(serve/_private/deployment_graph_build.py): `.bind(*args)` produces an
Application node; bound child nodes become DeploymentHandles injected into
the parent's constructor at deploy time (model composition).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple, Union


@dataclasses.dataclass
class AutoscalingConfig:
    """reference serve/config.py AutoscalingConfig (queue-metric driven)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 10.0


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    # Queue bound ABOVE the replicas' max_ongoing capacity: requests past
    # num_replicas*max_ongoing + max_queued_requests shed with
    # BackPressureError (HTTP 503 + Retry-After). None defers to the
    # RTPU_SERVE_MAX_QUEUED flag default; -1 means unbounded (reference:
    # Serve max_queued_requests, handle-side).
    max_queued_requests: Optional[int] = None
    ray_actor_options: Dict[str, Any] = dataclasses.field(
        default_factory=dict)
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 10.0
    user_config: Optional[Dict[str, Any]] = None
    # Handler returns a generator; calls stream item-by-item and the HTTP
    # proxy writes a chunked response (reference: serve streaming responses).
    stream: bool = False
    # Signal-driven autoscaling (serve/autoscaler.py ScalingPolicy or its
    # dict form): replica count follows queue depth / slot occupancy /
    # TTFT p99 through the AlertEngine machinery. Orthogonal to the
    # legacy queue-length autoscaling_config.
    scaling_policy: Optional[Dict[str, Any]] = None
    # Pool label for the disaggregated LLM plane ("prefill" | "decode");
    # rides into the per-pool replica-count gauge.
    pool: Optional[str] = None


class Deployment:
    def __init__(self, func_or_class: Union[type, Callable], name: str,
                 config: DeploymentConfig):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config

    def options(self, **kwargs) -> "Deployment":
        cfg = dataclasses.replace(self.config)
        name = kwargs.pop("name", self.name)
        for k, v in kwargs.items():
            if not hasattr(cfg, k):
                raise AttributeError(f"unknown deployment option {k!r}")
            setattr(cfg, k, v)
        return Deployment(self.func_or_class, name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __repr__(self) -> str:
        return f"Deployment({self.name})"


class Application:
    """A bound deployment DAG node (reference dag/dag_node.py ClassNode)."""

    def __init__(self, deployment: Deployment, args: Tuple, kwargs: Dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def _flatten(self, out: Optional[List["Application"]] = None
                 ) -> List["Application"]:
        """Topological list, children first."""
        out = out if out is not None else []
        for a in list(self.args) + list(self.kwargs.values()):
            if isinstance(a, Application):
                a._flatten(out)
        if self not in out:
            out.append(self)
        return out


def deployment(
    _func_or_class: Optional[Union[type, Callable]] = None,
    *,
    name: Optional[str] = None,
    num_replicas: Optional[int] = None,
    max_ongoing_requests: Optional[int] = None,
    max_queued_requests: Optional[int] = None,
    ray_actor_options: Optional[Dict[str, Any]] = None,
    autoscaling_config: Optional[Union[AutoscalingConfig, Dict]] = None,
    user_config: Optional[Dict[str, Any]] = None,
    stream: bool = False,
    scaling_policy: Optional[Dict[str, Any]] = None,
    pool: Optional[str] = None,
):
    """@serve.deployment decorator (reference serve/api.py:deployment)."""

    def wrap(fc):
        cfg = DeploymentConfig()
        cfg.stream = bool(stream)
        if scaling_policy is not None:
            cfg.scaling_policy = dict(scaling_policy)
        if pool is not None:
            cfg.pool = str(pool)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if max_queued_requests is not None:
            cfg.max_queued_requests = max_queued_requests
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if autoscaling_config is not None:
            ac = autoscaling_config
            cfg.autoscaling_config = (
                ac if isinstance(ac, AutoscalingConfig)
                else AutoscalingConfig(**ac))
        if user_config is not None:
            cfg.user_config = dict(user_config)
        return Deployment(fc, name or fc.__name__, cfg)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
