"""Per-request serving trace plane (RTPU_SERVE_TRACE).

Every hop a request crosses — proxy ingress, router assign, replica
execution, @serve.batch seal, engine slot wait, prefill, KV handoff,
decode attach, the token stream itself — emits a *hop span* measured on
that host's OWN monotonic clock (wall-clock start for display, monotonic
dwell for attribution — cross-host clock skew can shift a bar, never
stretch it). Trace identity is W3C ``traceparent`` (util/tracing.py
SpanContext) riding the serve request context (serve/context.py), so
nested handle composition and the disagg prefill→decode handoff share
one trace_id without threading kwargs through user code.

The process that CREATES a trace (HTTP/gRPC proxy, or a bare handle call
from a driver) owns the request's *ledger record*: terminal status
(ok / error / shed / deadline / cancelled), end-to-end wall, and the SLO
verdict. Spans and records buffer in a bounded per-process ring and ship
to the controller over the worker's reconnecting client (the
core/task_events.py flight-recorder shape): a batch in flight when the
controller dies re-buffers and delivers after the bounce. The controller
folds them into the request ledger (``rtpu serve requests`` /
``rtpu serve trace REQUEST_ID`` / ``state.list_serve_requests()``).

Everything is gated on ``RTPU_SERVE_TRACE`` (default on): when off, each
hop pays exactly one flag check and nothing is allocated, buffered, or
shipped.
"""
from __future__ import annotations

import collections
import secrets
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu import flags

from . import context as serve_context

_BUF_CAP = 4096  # per-process span/record ring bound (matches tracing)


def enabled() -> bool:
    return bool(flags.get("RTPU_SERVE_TRACE"))


def new_request_id() -> str:
    return secrets.token_hex(8)


def _traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def current_trace_ctx() -> Optional[Dict[str, str]]:
    """Wire form of the active request's trace identity (what rides the
    replica call next to deadline_ts/queue_wait): {"traceparent",
    "request_id", "deployment"}. None when no traced request is active —
    the callee then starts its own trace if it is an ingress."""
    c = serve_context.get_request_context()
    if not c or not c.get("trace_id"):
        return None
    return {"traceparent": _traceparent(c["trace_id"],
                                        c.get("parent_span_id")
                                        or "0" * 16),
            "request_id": c.get("request_id") or "",
            "deployment": c.get("deployment") or ""}


# ---------------------------------------------------------------- shipping

class _Shipper:
    """Bounded per-process buffer of hop spans + ledger records with a
    daemon flusher (the core/task_events.py _Recorder shape, pointed at
    the controller's serve_request_events ingest)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.spans: Optional[collections.deque] = None   # created lazily
        self.records: Optional[collections.deque] = None
        self._thread: Optional[threading.Thread] = None
        self._thread_up = False

    def add(self, span: Optional[Dict[str, Any]] = None,
            record: Optional[Dict[str, Any]] = None) -> None:
        with self.lock:
            if span is not None:
                if self.spans is None:
                    self.spans = collections.deque(maxlen=_BUF_CAP)
                self.spans.append(span)
            if record is not None:
                if self.records is None:
                    self.records = collections.deque(maxlen=_BUF_CAP)
                self.records.append(record)
        if not self._thread_up:
            self._ensure_flusher()

    def _ensure_flusher(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread_up = True
        self._thread = threading.Thread(
            target=self._run, name="rtpu-serve-trace-flush", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            time.sleep(flags.get("RTPU_TASK_EVENTS_FLUSH_S"))
            try:
                self.flush()
            except Exception:
                pass  # the trace plane must never take a replica down

    def flush(self, timeout: float = 30.0) -> bool:
        """Ship everything buffered; False (and re-buffer) on failure."""
        from ray_tpu.core import context as ctx

        with self.lock:
            spans = list(self.spans) if self.spans else []
            records = list(self.records) if self.records else []
            if self.spans is not None:
                self.spans.clear()
            if self.records is not None:
                self.records.clear()
        if not spans and not records:
            return True
        if not ctx.is_initialized():
            self._requeue(spans, records)
            return False
        try:
            wc = ctx.get_worker_context()
            wc.client.request({"kind": "serve_request_events",
                               "spans": spans, "records": records},
                              timeout=timeout)
            return True
        except Exception:
            self._requeue(spans, records)
            return False

    def _requeue(self, spans: List[Dict[str, Any]],
                 records: List[Dict[str, Any]]) -> None:
        with self.lock:
            if spans:
                if self.spans is None:
                    self.spans = collections.deque(maxlen=_BUF_CAP)
                self.spans.extendleft(reversed(spans))
            if records:
                if self.records is None:
                    self.records = collections.deque(maxlen=_BUF_CAP)
                self.records.extendleft(reversed(records))


_shipper = _Shipper()


def flush_serve_trace(timeout: float = 30.0) -> bool:
    """Force a flush of buffered spans/records (tests, shutdown hooks)."""
    return _shipper.flush(timeout=timeout)


def _ship_span(d: Dict[str, Any]) -> None:
    _shipper.add(span=d)
    # With the generic tracing plane on, serve hops also land in the
    # per-process finished-span record so get_cluster_spans(trace_id)
    # merges them with task spans sharing the same traceparent.
    try:
        from ray_tpu.util import tracing

        if tracing.enabled():
            sp = tracing.Span(
                name=d["name"],
                context=tracing.SpanContext(d["trace_id"], d["span_id"]),
                parent_span_id=d.get("parent_span_id", ""),
                kind=d.get("kind", "internal"),
                attributes=dict(d.get("attributes") or {}),
                start_time=d["start_ts"])
            sp.end_time = d["start_ts"] + d.get("dwell_s", 0.0)
            with tracing._finished_lock:
                tracing._finished.append(sp)
                del tracing._finished[:-4096]
    except Exception:
        pass


# ------------------------------------------------------------------ metrics

_metrics_cache: Optional[Dict[str, Any]] = None


def _metrics() -> Dict[str, Any]:
    global _metrics_cache
    if _metrics_cache is None:
        from ray_tpu.util import metrics

        _metrics_cache = {
            "requests": metrics.Counter(
                "rtpu_serve_requests_total",
                description="Finished serve requests by terminal status "
                            "(ok / error / shed / deadline / cancelled), "
                            "counted where the request's trace was "
                            "rooted (proxy or calling driver).",
                tag_keys=("deployment", "status")),
            "slo_miss": metrics.Counter(
                "rtpu_serve_slo_miss_total",
                description="Serve requests that missed the latency SLO: "
                            "end-to-end wall above RTPU_SERVE_SLO_MS, or "
                            "a shed / deadline-exceeded outcome. These "
                            "rows are retained ahead of LRU eviction in "
                            "the controller request ledger.",
                tag_keys=("deployment",)),
        }
    return _metrics_cache


# ------------------------------------------------------------------- spans

class Hop:
    """One in-flight hop span. ``end()`` stamps the dwell from this
    host's monotonic clock and ships the span; while open, child hops
    (and downstream trace_ctx) parent under it via the serve context."""

    __slots__ = ("name", "kind", "trace_id", "span_id", "parent_span_id",
                 "request_id", "deployment", "start_ts", "_mono0",
                 "attributes", "_ctx", "_prev_parent", "_done")

    def __init__(self, name: str, kind: str, trace_id: str,
                 parent_span_id: str, request_id: str, deployment: str,
                 attributes: Optional[Dict[str, Any]],
                 ctx: Optional[dict]) -> None:
        self.name = name
        self.kind = kind
        self.trace_id = trace_id
        self.span_id = secrets.token_hex(8)
        self.parent_span_id = parent_span_id
        self.request_id = request_id
        self.deployment = deployment
        self.start_ts = time.time()
        self._mono0 = time.monotonic()
        self.attributes = dict(attributes) if attributes else {}
        self._ctx = ctx
        self._prev_parent = None
        self._done = False
        if ctx is not None:
            self._prev_parent = ctx.get("parent_span_id")
            ctx["parent_span_id"] = self.span_id

    @property
    def trace_ctx(self) -> Dict[str, str]:
        return {"traceparent": _traceparent(self.trace_id, self.span_id),
                "request_id": self.request_id,
                "deployment": self.deployment}

    def end(self, **attrs: Any) -> None:
        if self._done:
            return
        self._done = True
        if self._ctx is not None:
            self._ctx["parent_span_id"] = self._prev_parent
        if attrs:
            self.attributes.update(attrs)
        _ship_span({
            "name": self.name, "kind": self.kind,
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_span_id": self.parent_span_id or "",
            "request_id": self.request_id, "deployment": self.deployment,
            "start_ts": self.start_ts,
            "dwell_s": max(0.0, time.monotonic() - self._mono0),
            "attributes": self.attributes,
        })

    def __enter__(self) -> "Hop":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


def start_hop(name: str, *, kind: str = "internal",
              attributes: Optional[Dict[str, Any]] = None,
              trace_ctx: Optional[Dict[str, str]] = None,
              deployment: str = "") -> Optional[Hop]:
    """Open a hop span under the active request's trace. Identity comes
    from ``trace_ctx`` (explicit wire context — proxies, batch items)
    when given, else the installed serve request context. Returns None
    (one flag/dict check, nothing else) when the plane is disabled or no
    trace is active."""
    if not enabled():
        return None
    if trace_ctx is not None:
        tp = (trace_ctx.get("traceparent") or "").split("-")
        if len(tp) != 4:
            return None
        return Hop(name, kind, tp[1], tp[2],
                   trace_ctx.get("request_id") or "",
                   deployment or trace_ctx.get("deployment") or "",
                   attributes, None)
    c = serve_context.get_request_context()
    if not c or not c.get("trace_id"):
        return None
    return Hop(name, kind, c["trace_id"],
               c.get("parent_span_id") or "",
               c.get("request_id") or "",
               deployment or c.get("deployment") or "",
               attributes, c)


def emit_span(name: str, *, trace_ctx: Optional[Dict[str, str]],
              dwell_s: float, start_ts: Optional[float] = None,
              kind: str = "internal",
              attributes: Optional[Dict[str, Any]] = None,
              deployment: str = "") -> None:
    """Ship a hop span measured out-of-band (the caller already holds the
    monotonic dwell — batch-queue dwell between submit and seal, a KV
    handoff's transfer time). No-op when the plane is off or the wire
    context is absent/malformed."""
    if not enabled() or not trace_ctx:
        return
    tp = (trace_ctx.get("traceparent") or "").split("-")
    if len(tp) != 4:
        return
    dwell_s = max(0.0, float(dwell_s))
    _ship_span({
        "name": name, "kind": kind,
        "trace_id": tp[1], "span_id": secrets.token_hex(8),
        "parent_span_id": tp[2],
        "request_id": trace_ctx.get("request_id") or "",
        "deployment": deployment or trace_ctx.get("deployment") or "",
        "start_ts": (time.time() - dwell_s
                     if start_ts is None else start_ts),
        "dwell_s": dwell_s,
        "attributes": dict(attributes) if attributes else {},
    })


# ------------------------------------------------------------- trace roots

#: Terminal statuses a ledger record may carry.
STATUSES = ("ok", "error", "shed", "deadline", "cancelled")


class RootTrace:
    """The outermost hop of a request — owned by whichever process
    created the trace_id (HTTP/gRPC proxy, or Router.assign for a bare
    driver-side handle call). ``finish()`` emits the root span AND the
    ledger record (terminal status, end-to-end wall, SLO verdict) and
    bumps rtpu_serve_requests_total / rtpu_serve_slo_miss_total."""

    __slots__ = ("trace_id", "span_id", "request_id", "deployment",
                 "proto", "method", "start_ts", "_mono0", "attributes",
                 "_done")

    def __init__(self, request_id: str, deployment: str, proto: str,
                 method: str) -> None:
        self.trace_id = secrets.token_hex(16)
        self.span_id = secrets.token_hex(8)
        self.request_id = request_id or new_request_id()
        self.deployment = deployment
        self.proto = proto
        self.method = method
        self.start_ts = time.time()
        self._mono0 = time.monotonic()
        self.attributes: Dict[str, Any] = {}
        self._done = False

    @property
    def trace_ctx(self) -> Dict[str, str]:
        return {"traceparent": _traceparent(self.trace_id, self.span_id),
                "request_id": self.request_id,
                "deployment": self.deployment}

    def finish(self, status: str = "ok", error: str = "",
               **attrs: Any) -> None:
        """Idempotent: the first terminal outcome wins (a streaming
        response closed after exhaustion stays "ok")."""
        if self._done:
            return
        self._done = True
        wall = max(0.0, time.monotonic() - self._mono0)
        if attrs:
            self.attributes.update(attrs)
        _ship_span({
            "name": f"serve.{self.proto}", "kind": "ingress",
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_span_id": "",
            "request_id": self.request_id, "deployment": self.deployment,
            "start_ts": self.start_ts, "dwell_s": wall,
            "attributes": self.attributes,
        })
        slo_ms = flags.get("RTPU_SERVE_SLO_MS")
        miss = (status in ("shed", "deadline")
                or (slo_ms and slo_ms > 0 and wall * 1e3 > slo_ms))
        record = {
            "request_id": self.request_id, "trace_id": self.trace_id,
            "deployment": self.deployment, "method": self.method,
            "proto": self.proto, "status": status,
            "error": (error or "")[:512],
            "start_ts": self.start_ts, "wall_s": wall,
            "slo_miss": bool(miss),
        }
        try:
            m = _metrics()
            dep = self.deployment or "unknown"
            m["requests"].inc(
                1, tags={"deployment": dep, "status": status})
            if miss:
                m["slo_miss"].inc(1, tags={"deployment": dep})
        except Exception:
            pass
        _shipper.add(record=record)


def start_request(*, request_id: str = "", deployment: str = "",
                  proto: str = "python",
                  method: str = "") -> Optional[RootTrace]:
    """Root a new trace at an ingress. None when the plane is off."""
    if not enabled():
        return None
    return RootTrace(request_id, deployment, proto, method)


# ------------------------------------------------------------ stall stacks

def capture_stacks(max_chars: int = 16384) -> str:
    """All-thread stack capture for STREAM_STALLED events (the hang
    watchdog's attachment shape — core/worker.py _format_stacks)."""
    import sys
    import traceback

    out = []
    try:
        frames = sys._current_frames()
        for tid, frame in list(frames.items()):
            out.append(f"--- thread {tid} ---")
            out.append("".join(traceback.format_stack(frame)))
    except Exception as e:  # capture must never raise into the hot path
        out.append(f"<stack capture failed: {e}>")
    return "\n".join(out)[:max_chars]
