"""Model multiplexing: many models share one deployment's replicas.

Parity: reference serve/api.py @serve.multiplexed +
serve.get_multiplexed_model_id (serve/_private/... model multiplex wrapper
with per-replica LRU) and model-affinity routing. The loader is wrapped
with a per-replica LRU cache; requests carry a model id, the router keeps
per-model affinity (rendezvous hash over healthy replicas) so repeated
requests for one model land where it is already loaded.
"""
from __future__ import annotations

import contextvars
import functools
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

_model_id_ctx: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "rtpu_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """Inside a replica handling a multiplexed request: the model id the
    caller asked for (reference serve.get_multiplexed_model_id)."""
    return _model_id_ctx.get()


def _set_model_id(model_id: str):
    return _model_id_ctx.set(model_id or "")


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorate a model-loader method: calls are cached per model id in a
    per-replica LRU of size max_num_models_per_replica; evicted models are
    dropped (their __del__ releases resources)."""

    def wrap(loader: Callable) -> Callable:
        # Cache + lock are created LAZILY in the replica process (stored on
        # the instance, or in this module for free functions): the decorated
        # class is cloudpickled to replicas, and a Lock captured in the
        # closure would make it unpicklable.
        state_attr = f"_rtpu_mux_{loader.__name__}"

        def _state(owner):
            st = getattr(owner, state_attr, None)
            if st is None:
                st = {"lock": threading.Lock(), "cache": OrderedDict()}
                setattr(owner, state_attr, st)
            return st

        @functools.wraps(loader)
        def wrapper(self_or_id=None, model_id: Optional[str] = None):
            # Support both method (self, model_id?) and free-function forms.
            if isinstance(self_or_id, str) and model_id is None:
                bound_self, mid = None, self_or_id
            else:
                bound_self, mid = self_or_id, model_id
            if mid is None:
                mid = get_multiplexed_model_id()
            if not mid:
                raise ValueError(
                    "no model id: pass one or call via "
                    "handle.options(multiplexed_model_id=...)")
            st = _state(bound_self if bound_self is not None else wrapper)
            lock, cache = st["lock"], st["cache"]
            with lock:
                if mid in cache:
                    cache.move_to_end(mid)
                    return cache[mid]
            model = loader(bound_self, mid) if bound_self is not None \
                else loader(mid)
            with lock:
                cache[mid] = model
                cache.move_to_end(mid)
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)
            return model

        wrapper._rtpu_multiplexed = True  # noqa: SLF001
        return wrapper

    if func is not None:
        return wrap(func)
    return wrap
