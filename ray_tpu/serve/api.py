"""Public serve API: run/shutdown/get_handle + HTTP ingress.

Parity: reference serve/api.py (serve.run :545, serve.start, serve.delete,
serve.get_app_handle/get_deployment_handle). serve.run deploys an
Application graph: bound child nodes become DeploymentHandles injected into
parent constructors (deployment_graph_build.py), the controller reconciles
replicas, and (optionally) an HTTP proxy exposes the ingress deployment.
"""
from __future__ import annotations

import atexit
import logging
import time
from typing import Any, Dict, Optional

import cloudpickle

import ray_tpu

from .controller import CONTROLLER_NAME, ServeController
from .deployment import Application, Deployment
from .handle import DeploymentHandle
from .proxy import HTTPProxy

logger = logging.getLogger(__name__)

_proxy: Optional[HTTPProxy] = None
_grpc_proxy = None


def _get_controller_if_exists():
    """The running controller actor, or None — never creates one and never
    boots a cluster (read-only probes must stay side-effect free)."""
    if not ray_tpu.is_initialized():
        return None
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return None


def _get_or_create_controller():
    if not ray_tpu.is_initialized():
        ray_tpu.init(ignore_reinit_error=True)
    ctrl = _get_controller_if_exists()
    if ctrl is not None:
        return ctrl
    ctrl = ray_tpu.remote(ServeController).options(
        name=CONTROLLER_NAME, num_cpus=0.1, max_concurrency=8).remote()
    ray_tpu.get(ctrl.ping.remote())
    atexit.register(shutdown)
    return ctrl


def start(*, http_host: str = "127.0.0.1", http_port: int = 8000,
          detached: bool = False) -> None:
    """Start serve (controller + HTTP proxy) without deploying anything."""
    global _proxy
    _get_or_create_controller()
    if _proxy is None:
        _proxy = HTTPProxy(http_host, http_port)
        _proxy.start()


def run(target: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/", blocking: bool = False,
        _http: bool = False, http_port: int = 8000,
        _grpc: bool = False, grpc_port: int = 9000) -> DeploymentHandle:
    """Deploy an application graph; returns a handle to the ingress
    deployment. `_http=True` also starts the HTTP proxy on http_port;
    `_grpc=True` starts the gRPC ingress (JSON-envelope generic service,
    grpc_proxy.py) on grpc_port."""
    if not isinstance(target, Application):
        raise TypeError("serve.run expects Deployment.bind(...)")
    ctrl = _get_or_create_controller()

    nodes = target._flatten()
    for node in nodes:
        dep = node.deployment
        # Replace bound child nodes with handles to their deployments.
        args = tuple(
            DeploymentHandle(a.deployment.name) if isinstance(a, Application)
            else a
            for a in node.args)
        kwargs = {
            k: (DeploymentHandle(v.deployment.name)
                if isinstance(v, Application) else v)
            for k, v in node.kwargs.items()}
        cfg = {
            "num_replicas": dep.config.num_replicas,
            "max_ongoing_requests": dep.config.max_ongoing_requests,
            "max_queued_requests": dep.config.max_queued_requests,
            "ray_actor_options": dep.config.ray_actor_options,
            "user_config": dep.config.user_config,
            "autoscaling_config": (
                vars(dep.config.autoscaling_config)
                if dep.config.autoscaling_config else None),
            "stream": dep.config.stream,
            "scaling_policy": dep.config.scaling_policy,
            "pool": dep.config.pool,
        }
        prefix = route_prefix if node is target else None
        ray_tpu.get(ctrl.deploy.remote(
            dep.name, cloudpickle.dumps(dep.func_or_class),
            args, kwargs, cfg, prefix))

    # Wait for the ingress deployment to have live replicas; a deployment
    # whose constructor keeps failing must raise with the real error, not
    # hand back a handle that can never route.
    from ray_tpu import flags

    ready_timeout = flags.get("RTPU_SERVE_READY_TIMEOUT_S")
    deadline = time.time() + ready_timeout
    while True:
        _, reps = ray_tpu.get(
            ctrl.get_replicas.remote(target.deployment.name))
        if reps:
            break
        if time.time() > deadline:
            err = ray_tpu.get(
                ctrl.get_last_error.remote(target.deployment.name))
            raise RuntimeError(
                f"deployment {target.deployment.name!r} has no live "
                f"replicas after {ready_timeout:g}s; last replica error: "
                f"{err}")
        time.sleep(0.1)
    if _http:
        start(http_port=http_port)
    if _grpc:
        global _grpc_proxy
        if _grpc_proxy is None:
            from .grpc_proxy import GRPCProxy

            _grpc_proxy = GRPCProxy(port=grpc_port)
            _grpc_proxy.start()
    handle = DeploymentHandle(target.deployment.name)
    if blocking:
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
    return handle


def get_app_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


get_deployment_handle = get_app_handle


def delete(name: str) -> None:
    ctrl = _get_or_create_controller()
    ray_tpu.get(ctrl.delete_deployment.remote(name))


def status() -> Optional[Dict[str, Any]]:
    """Read-only: never creates a controller or boots a cluster. Returns
    None when serve is not running (or no cluster is attached), {} when
    serve runs with zero deployments — callers can tell the two apart
    (reference `serve status` draws the same distinction)."""
    ctrl = _get_controller_if_exists()
    if ctrl is None:
        return None
    names = ray_tpu.get(ctrl.get_deployment_names.remote())
    out: Dict[str, Any] = {}
    for n in names:
        version, reps = ray_tpu.get(ctrl.get_replicas.remote(n))
        out[n] = {"version": version, "num_replicas": len(reps)}
    return out


def shutdown() -> None:
    global _proxy, _grpc_proxy
    if _proxy is not None:
        try:
            _proxy.stop()
        except Exception:
            pass
        _proxy = None
    if _grpc_proxy is not None:
        try:
            _grpc_proxy.stop()
        except Exception:
            pass
        _grpc_proxy = None
    ctrl = _get_controller_if_exists()
    if ctrl is None:
        return
    try:
        ray_tpu.get(ctrl.shutdown.remote(), timeout=15)
        ray_tpu.kill(ctrl)
    except Exception:
        pass
