"""ReplicaActor: hosts one copy of the user's deployment callable.

Parity: reference serve/_private/replica.py:231 (ReplicaActor,
UserCallableWrapper :737): constructs the user class (or wraps the
function), executes requests, tracks ongoing-request count for the
power-of-two router and the autoscaler, and exposes health checks +
user_config reconfiguration.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import cloudpickle


class ReplicaActor:
    def __init__(self, serialized_callable: bytes, init_args: Tuple,
                 init_kwargs: Dict, user_config: Optional[Dict] = None):
        func_or_class = cloudpickle.loads(serialized_callable)
        if isinstance(func_or_class, type):
            self._callable = func_or_class(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = func_or_class
            self._is_function = True
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        if user_config is not None:
            self.reconfigure(user_config)

    # ---------------------------------------------------------------- serving

    @staticmethod
    def _check_deadline(deadline_ts, where: str):
        """Pre-execution expiry gate: an expired request is dropped with
        the typed error instead of burning replica capacity."""
        if deadline_ts is not None and time.time() > deadline_ts:
            from ray_tpu.core.controller import DeadlineExceededError

            raise DeadlineExceededError(
                f"request deadline passed {where}")

    def handle_request(self, method_name: str, args: Tuple, kwargs: Dict,
                       multiplexed_model_id: str = "",
                       deadline_ts: Optional[float] = None,
                       start_ts: Optional[float] = None,
                       queue_wait_s: float = 0.0,
                       trace_ctx: Optional[Dict] = None):
        from . import context as serve_context
        from . import trace
        from .multiplex import _set_model_id

        self._check_deadline(deadline_ts, "before replica execution")
        with self._lock:
            self._ongoing += 1
            self._total += 1
        token = _set_model_id(multiplexed_model_id)
        ctx_token = serve_context.set_request_context(
            deadline_ts=deadline_ts, start_ts=start_ts,
            queue_wait_s=queue_wait_s, trace_ctx=trace_ctx)
        # The replica hop measures execution on THIS host's clock; the
        # upstream queue accumulation it rode in on (router dwell + the
        # mailbox) is attached so the waterfall can attribute the gap
        # between the router's dispatch and this span's start.
        hop = trace.start_hop(
            "serve.replica", kind="replica",
            attributes={"method": method_name,
                        "queue_wait_s": round(queue_wait_s or 0.0, 6)})
        try:
            if self._is_function:
                return self._callable(*args, **kwargs)
            if method_name == "__call__":
                return self._callable(*args, **kwargs)
            return getattr(self._callable, method_name)(*args, **kwargs)
        except BaseException as e:
            if hop is not None:
                hop.end(error=type(e).__name__)
                hop = None
            raise
        finally:
            from .multiplex import _model_id_ctx

            if hop is not None:
                hop.end()
            serve_context.reset_request_context(ctx_token)
            _model_id_ctx.reset(token)
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(self, method_name: str, args: Tuple,
                                 kwargs: Dict,
                                 multiplexed_model_id: str = "",
                                 deadline_ts: Optional[float] = None,
                                 start_ts: Optional[float] = None,
                                 queue_wait_s: float = 0.0,
                                 trace_ctx: Optional[Dict] = None):
        """Generator variant: the user handler returns a generator/iterable
        whose items stream to the caller one object at a time (reference:
        serve streaming responses over streaming generator returns,
        serve/_private/replica.py handle_request_streaming)."""
        from . import context as serve_context
        from . import trace
        from .multiplex import _set_model_id

        self._check_deadline(deadline_ts, "before replica execution")
        with self._lock:
            self._ongoing += 1
            self._total += 1
        _set_model_id(multiplexed_model_id)
        ctx_token = serve_context.set_request_context(
            deadline_ts=deadline_ts, start_ts=start_ts,
            queue_wait_s=queue_wait_s, trace_ctx=trace_ctx)
        # Covers the stream's whole replica-side life: opened before the
        # user generator starts, ended when it exhausts or the consumer
        # walks away (GeneratorExit lands in the finally).
        hop = trace.start_hop(
            "serve.replica", kind="replica",
            attributes={"method": method_name, "stream": True,
                        "queue_wait_s": round(queue_wait_s or 0.0, 6)})
        items = 0
        try:
            if self._is_function:
                result = self._callable(*args, **kwargs)
            elif method_name == "__call__":
                result = self._callable(*args, **kwargs)
            else:
                result = getattr(self._callable, method_name)(*args, **kwargs)
            for item in result:
                items += 1
                yield item
        except BaseException as e:
            if hop is not None:
                hop.end(error=type(e).__name__, items=items)
                hop = None
            raise
        finally:
            if hop is not None:
                hop.end(items=items)
            serve_context.reset_request_context(ctx_token)
            with self._lock:
                self._ongoing -= 1

    # ----------------------------------------------------------------- state

    def queue_len(self) -> int:
        return self._ongoing

    def stats(self) -> Dict[str, Any]:
        """Replica load snapshot. When the user callable exposes a
        ``serve_stats()`` protocol (the LLM engine deployments do: slot
        occupancy, blocked submitters, prefix-cache hit rates), its dict
        is merged in under ``serve`` — the controller's signal poll and
        the autoscaler read it from here."""
        out: Dict[str, Any] = {"ongoing": self._ongoing,
                               "total": self._total}
        fn = getattr(self._callable, "serve_stats", None)
        if callable(fn):
            try:
                out["serve"] = fn()
            except Exception:
                pass
        return out

    def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if callable(user_check):
            user_check()
        return True

    def reconfigure(self, user_config: Dict) -> None:
        fn = getattr(self._callable, "reconfigure", None)
        if callable(fn):
            fn(user_config)

    def prepare_shutdown(self) -> None:
        fn = getattr(self._callable, "__del__", None)
        if callable(fn):
            try:
                fn()
            except Exception:
                pass
