"""Signal-driven serve autoscaler: pool sizes follow serving signals.

Parity: reference serve autoscaling_state.py's queue-metric policy, but
driven by the SERVING signals the ROADMAP calls out — queue depth
(submitters blocked on a slot), slot occupancy, and TTFT p99 — instead
of raw ongoing-request counts, and evaluated through the telemetry
plane's AlertEngine so scaling triggers get the same threshold +
for-duration semantics (and the same tested state machine) as alert
rules (core/telemetry.py).

Each deployment that sets a ``scaling_policy`` gets a private rule set:

- scale_up_queue:  queue depth >= queue_depth_high for up_for_s
- scale_up_occ:    slot occupancy >= occupancy_high for up_for_s
- scale_up_ttft:   TTFT p99 >= ttft_p99_high_s for up_for_s (optional)
- scale_down:      queue <= queue_depth_low AND occupancy <=
                   occupancy_low, sustained for down_for_s (the AND is
                   folded into one derived idle gauge so the engine's
                   per-rule machinery stays unchanged)

A firing rule becomes a ±1 replica step (per-deployment cooldown bounds
churn); the fired state is then reset so SUSTAINED pressure re-fires
after another full for-duration window — stepwise scaling, not one-shot.
The controller applies up-steps through the normal deployment path and
down-steps by DRAINING a replica (PR 4 machinery): routers drop it on
the version bump, the actor dies only once idle, so no stream is cut.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import flags
from ray_tpu.core.telemetry import AlertEngine, MetricsTSDB

logger = logging.getLogger(__name__)

_scale_metrics_cache = None


def _scale_metrics():
    global _scale_metrics_cache
    if _scale_metrics_cache is None:
        from ray_tpu.util.metrics import Counter

        _scale_metrics_cache = {
            "events": Counter(
                "rtpu_serve_scale_events_total",
                description="Serve autoscaler replica-count steps taken "
                            "(direction label: up | down)",
                tag_keys=("deployment", "direction")),
        }
    return _scale_metrics_cache


@dataclasses.dataclass
class ScalingPolicy:
    """Per-deployment autoscaling policy (the ``scaling_policy`` config
    key; dicts coerce through ``ScalingPolicy(**d)``)."""

    min_replicas: int = 1
    max_replicas: int = 4
    queue_depth_high: float = 4.0
    queue_depth_low: float = 0.5
    occupancy_high: float = 0.95
    occupancy_low: float = 0.5
    # <= 0 disables the TTFT trigger (telemetry may be off entirely).
    ttft_p99_high_s: float = 0.0
    up_for_s: float = 2.0
    down_for_s: float = 10.0
    # < 0 defers to the RTPU_SERVE_SCALE_COOLDOWN_S flag.
    cooldown_s: float = -1.0


def _tags(name: str) -> Tuple[Tuple[str, str], ...]:
    return (("deployment", name),)


class ServeAutoscaler:
    """Owns a private MetricsTSDB ring + AlertEngine evaluated over the
    controller's per-deployment signal polls. step() returns the replica
    deltas to apply this tick."""

    def __init__(self, step_s: float = 1.0, retain: int = 600):
        self._tsdb = MetricsTSDB(step_s=step_s, retain=retain)
        self._policies: Dict[str, ScalingPolicy] = {}
        self._engine = AlertEngine([], self._on_event)
        self._pending: List[Tuple[str, int]] = []
        self._reset_keys: List[Any] = []
        self._last_action: Dict[str, float] = {}
        self._now = 0.0

    # ---------------------------------------------------------- policies

    def configure(self, name: str, policy) -> Optional[ScalingPolicy]:
        """Register/refresh a deployment's policy (dict or ScalingPolicy;
        None/falsy forgets it). Returns the coerced policy."""
        if not policy:
            self.forget(name)
            return None
        if isinstance(policy, dict):
            policy = ScalingPolicy(**policy)
        self._policies[name] = policy
        self._engine.rules = self._build_rules()
        return policy

    def forget(self, name: str) -> None:
        if self._policies.pop(name, None) is not None:
            self._engine.rules = self._build_rules()
            self._last_action.pop(name, None)

    def policy(self, name: str) -> Optional[ScalingPolicy]:
        return self._policies.get(name)

    def _build_rules(self) -> List[dict]:
        rules: List[dict] = []
        for name, p in self._policies.items():
            tags = {"deployment": name}
            rules.append({
                "name": f"scale_up_queue:{name}",
                "metric": "serve_queue_depth", "tags": tags, "op": ">=",
                "threshold": p.queue_depth_high, "for_s": p.up_for_s,
                "severity": "INFO",
                "message": "queue depth sustained above policy high"})
            rules.append({
                "name": f"scale_up_occ:{name}",
                "metric": "serve_slot_occupancy", "tags": tags,
                "op": ">=", "threshold": p.occupancy_high,
                "for_s": p.up_for_s, "severity": "INFO",
                "message": "slot occupancy sustained above policy high"})
            if p.ttft_p99_high_s > 0:
                rules.append({
                    "name": f"scale_up_ttft:{name}",
                    "metric": "serve_ttft_p99_s", "tags": tags,
                    "op": ">=", "threshold": p.ttft_p99_high_s,
                    "for_s": p.up_for_s, "severity": "INFO",
                    "message": "TTFT p99 sustained above policy high"})
            rules.append({
                "name": f"scale_down:{name}",
                "metric": "serve_idle", "tags": tags, "op": ">=",
                "threshold": 1.0, "for_s": p.down_for_s,
                "severity": "INFO",
                "message": "pool idle (low queue + low occupancy)"})
        return rules

    # ------------------------------------------------------------- events

    def _on_event(self, severity: str, event: str, msg: str,
                  data: Optional[dict] = None) -> None:
        if event != "ALERT_FIRING" or not data:
            return
        alert = str(data.get("alert", ""))
        tags = dict(data.get("tags") or {})
        name = tags.get("deployment")
        if not name or name not in self._policies:
            return
        delta = 1 if alert.startswith("scale_up") else -1
        # Re-arm regardless of cooldown: the state machine must be able
        # to fire again after another full for-duration window.
        self._reset_keys.append((alert, tuple(sorted(tags.items()))))
        p = self._policies[name]
        cooldown = (p.cooldown_s if p.cooldown_s >= 0
                    else flags.get("RTPU_SERVE_SCALE_COOLDOWN_S"))
        last = self._last_action.get(name, -1e18)
        if self._now - last < cooldown:
            return
        self._last_action[name] = self._now
        self._pending.append((name, delta))
        _scale_metrics()["events"].inc(
            1.0, tags={"deployment": name,
                       "direction": "up" if delta > 0 else "down"})
        logger.info("serve autoscaler: %s %+d (%s: %s)", name, delta,
                    alert, msg)

    # --------------------------------------------------------------- step

    def step(self, now: float,
             signals: Dict[str, Dict[str, float]]) -> Dict[str, int]:
        """One control tick. ``signals`` maps deployment name ->
        {"queue_depth", "occupancy", optional "ttft_p99_s"} from the
        controller's replica stats poll. Returns {name: ±1} deltas (the
        controller clamps to the policy's min/max and applies them)."""
        if not flags.get("RTPU_SERVE_AUTOSCALE") or not self._policies:
            return {}
        fams: Dict[str, dict] = {
            "serve_queue_depth": {"type": "gauge", "data": {}},
            "serve_slot_occupancy": {"type": "gauge", "data": {}},
            "serve_ttft_p99_s": {"type": "gauge", "data": {}},
            "serve_idle": {"type": "gauge", "data": {}},
        }
        for name, p in self._policies.items():
            sig = signals.get(name)
            if sig is None:
                continue
            t = _tags(name)
            q = float(sig.get("queue_depth", 0.0))
            occ = float(sig.get("occupancy", 0.0))
            fams["serve_queue_depth"]["data"][t] = q
            fams["serve_slot_occupancy"]["data"][t] = occ
            ttft = sig.get("ttft_p99_s")
            if ttft is not None:
                fams["serve_ttft_p99_s"]["data"][t] = float(ttft)
            idle = 1.0 if (q <= p.queue_depth_low
                           and occ <= p.occupancy_low) else 0.0
            fams["serve_idle"]["data"][t] = idle
        self._tsdb.sample(now, fams)
        self._now = now
        self._pending = []
        self._reset_keys = []
        self._engine.evaluate(now, self._tsdb)
        for key in self._reset_keys:
            self._engine.state.pop(key, None)
        out: Dict[str, int] = {}
        for name, delta in self._pending:
            out[name] = max(-1, min(1, out.get(name, 0) + delta))
        return {n: d for n, d in out.items() if d}
