"""HTTP proxy: route prefix -> deployment handle.

Parity: reference serve/_private/proxy.py:1112 (ProxyActor, HTTPProxy :748
ASGI). An aiohttp server runs on a dedicated thread (inside the driver or a
proxy actor); requests route by longest-prefix match against the
controller's route table and dispatch through the same DeploymentHandle /
power-of-two router as Python callers. JSON in/out; non-JSON bodies pass
through as text.
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu import flags
from ray_tpu.core.controller import DeadlineExceededError

from . import trace
from .admission import BackPressureError
from .controller import CONTROLLER_NAME
from .handle import DeploymentHandle, DeploymentNotFoundError


def _request_timeout_s(request) -> float:
    """Per-request end-to-end budget: X-Request-Timeout-S header when the
    client sends one, else the RTPU_SERVE_REQUEST_TIMEOUT_S flag default
    (the fix for the old hard-coded 60s)."""
    hdr = request.headers.get("X-Request-Timeout-S")
    if hdr:
        try:
            v = float(hdr)
            if v > 0:
                return v
        except ValueError:
            pass
    return float(flags.get("RTPU_SERVE_REQUEST_TIMEOUT_S"))


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        self._routes: Dict[str, str] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._runner = None
        # Streaming responses park a thread per open connection between
        # chunks; a dedicated pool keeps slow streams from starving the
        # default executor that serves every non-streaming request.
        from concurrent.futures import ThreadPoolExecutor

        self._stream_pool = ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="proxy-stream")

    # ----------------------------------------------------------------- serve

    def start(self) -> None:
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("HTTP proxy failed to start")

    def _refresh_routes(self) -> None:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
        self._routes = ray_tpu.get(ctrl.get_route_info.remote())

    def _match(self, path: str) -> Optional[Dict[str, Any]]:
        best = None
        for prefix, info in self._routes.items():
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") \
                    or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, info)
        return best[1] if best else None

    async def _handle(self, request):
        from aiohttp import web

        info = self._match(request.path)
        if info is None:
            self._refresh_routes()
            info = self._match(request.path)
        if info is None:
            return web.json_response(
                {"error": f"no route for {request.path}"}, status=404)
        name = info["name"]
        if request.method == "GET":
            arg: Any = dict(request.query)
        else:
            body = await request.read()
            try:
                arg = json.loads(body) if body else None
            except json.JSONDecodeError:
                arg = body.decode()
        handle = self._handles.setdefault(name, DeploymentHandle(name))
        timeout_s = _request_timeout_s(request)
        # Ingress stamping: the client's X-Request-Id or a generated one —
        # every ledger row / cancellation event downstream carries it, and
        # it echoes back on the response for log correlation. The proxy
        # owns the trace root, so the record's wall is true end-to-end
        # (handle dispatch + replica + result/stream relay).
        rid = request.headers.get("X-Request-Id") or trace.new_request_id()
        root = trace.start_request(request_id=rid, deployment=name,
                                   proto="http", method=request.method)
        tctx = root.trace_ctx if root is not None else None
        hdrs = {"X-Request-Id": rid}
        if info.get("stream"):
            return await self._handle_streaming(request, handle, name, arg,
                                                timeout_s, rid, root)
        try:
            # The deadline threads end-to-end: router admission, replica
            # dequeue, and batch seal all honor it — result() just waits
            # out the same budget.
            resp = await asyncio.get_running_loop().run_in_executor(
                None, lambda: handle.options(
                    deadline_s=timeout_s, request_id=rid, trace_ctx=tctx)
                .remote(arg).result())
        except DeploymentNotFoundError:
            # Deployment was deleted: drop the stale route + handle.
            self._handles.pop(name, None)
            self._refresh_routes()
            if root is not None:
                root.finish("error", error="deployment not found")
            return web.json_response(
                {"error": f"deployment {name} not found"}, status=404,
                headers=hdrs)
        except BackPressureError as e:
            if root is not None:
                root.finish("shed", error=str(e), http_status=503)
            return web.json_response(
                {"error": str(e)}, status=503,
                headers=dict(hdrs, **{"Retry-After":
                                      f"{max(1, round(e.retry_after_s))}"}))
        except DeadlineExceededError as e:
            if root is not None:
                root.finish("deadline", error=str(e), http_status=504)
            return web.json_response({"error": str(e)}, status=504,
                                     headers=hdrs)
        except Exception as e:
            if root is not None:
                root.finish("error", error=str(e), http_status=500)
            return web.json_response({"error": str(e)}, status=500,
                                     headers=hdrs)
        if root is not None:
            root.finish("ok", http_status=200)
        if isinstance(resp, (dict, list, int, float, bool)) or resp is None:
            return web.json_response({"result": resp}, headers=hdrs)
        return web.Response(text=str(resp), headers=hdrs)

    async def _handle_streaming(self, request, handle, name: str, arg,
                                timeout_s: Optional[float] = None,
                                rid: str = "", root=None):
        """Chunked-transfer response fed by a streaming deployment call
        (reference: serve HTTP streaming responses over the generator
        protocol). Each yielded item becomes one chunk; str/bytes pass
        through, anything else is JSON + newline. Client disconnect closes
        the deployment stream, which aborts the replica-side generator
        (GeneratorExit) and frees its engine slot immediately."""
        from aiohttp import web

        hdrs = {"X-Request-Id": rid} if rid else {}
        tctx = root.trace_ctx if root is not None else None
        loop = asyncio.get_running_loop()
        try:
            # assign() does blocking controller/replica RPCs — keep them off
            # the proxy event loop (the non-streaming path does the same).
            gen = await loop.run_in_executor(
                self._stream_pool,
                lambda: iter(handle.options(
                    stream=True, deadline_s=timeout_s, request_id=rid,
                    trace_ctx=tctx).remote(arg)))
        except BackPressureError as e:
            if root is not None:
                root.finish("shed", error=str(e), http_status=503)
            return web.json_response(
                {"error": str(e)}, status=503,
                headers=dict(hdrs, **{"Retry-After":
                                      f"{max(1, round(e.retry_after_s))}"}))
        except DeadlineExceededError as e:
            if root is not None:
                root.finish("deadline", error=str(e), http_status=504)
            return web.json_response({"error": str(e)}, status=504,
                                     headers=hdrs)
        except Exception as e:
            if root is not None:
                root.finish("error", error=str(e), http_status=500)
            return web.json_response({"error": str(e)}, status=500,
                                     headers=hdrs)
        resp = web.StreamResponse(headers=hdrs)
        resp.enable_chunked_encoding()
        await resp.prepare(request)
        _END = object()
        items = 0
        complete = False
        failed = deadline = False
        try:
            while True:
                try:
                    item = await loop.run_in_executor(
                        self._stream_pool, lambda: next(gen, _END))
                except DeadlineExceededError:
                    deadline = True
                    break
                except Exception:
                    failed = True
                    break  # mid-stream failure: terminate the chunked body
                if item is _END:
                    complete = True
                    break
                if isinstance(item, bytes):
                    data = item
                elif isinstance(item, str):
                    data = item.encode()
                else:
                    data = (json.dumps(item) + "\n").encode()
                await resp.write(data)
                items += 1
        finally:
            # Reached on normal end AND on client disconnect (aiohttp
            # raises/cancels out of resp.write): cancel the producer so a
            # walked-away client never keeps a KV slot warm.
            if root is not None:
                root.finish("ok" if complete
                            else "deadline" if deadline
                            else "error" if failed else "cancelled",
                            items=items)
            close = getattr(gen, "close", None)
            if close is not None:
                await loop.run_in_executor(self._stream_pool, close)
        await resp.write_eof()
        return resp

    def _run(self) -> None:
        from aiohttp import web

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app)

        async def _start():
            await self._runner.setup()
            site = web.TCPSite(self._runner, self.host, self.port)
            await site.start()

        self._loop.run_until_complete(_start())
        self._started.set()
        self._loop.run_forever()

    def stop(self) -> None:
        if self._loop is None:
            return

        async def _cleanup():
            await self._runner.cleanup()
            self._loop.stop()

        asyncio.run_coroutine_threadsafe(_cleanup(), self._loop)
        self._thread.join(timeout=5)
