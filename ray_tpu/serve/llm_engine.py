"""Continuous-batching generation engine (slot-based, vLLM-style shape).

The batch LLM deployment coalesces requests that ARRIVE together; this
engine lets requests join and leave a RUNNING batch: a fixed pool of B
slots shares one ragged KV cache (models/generate.py per-row positions),
every tick runs ONE decode_step over all slots, and a request attaches by
splicing its prefilled K/V into a free slot mid-flight. Short requests
retire without stalling long ones; new arrivals don't wait for the batch
to drain.

Compiled units (all static shapes, reused forever):
- per-length-bucket prefill of a single prompt,
- the slot splice (dynamic_update_slice on the batch axis),
- one decode tick (the [B] ragged decode_step + sampling).

The engine is deliberately serve-independent and synchronous-core: attach/
tick/poll are plain methods driven by one background thread, so it can be
tested exhaustively without actors and wired into any serving surface.
Inactive slots still compute through the tick (their rows are masked at
the sampling layer) — wasted FLOPs bounded by B, the price of a single
compiled program.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


_serve_metrics_cache = None


def _serve_metrics():
    """Lazy shared serve metrics (util/metrics.py plane; tagged by model
    so every engine in the process shares the three instruments). The
    ROADMAP serve item: TTFT p99 and tokens/s must be first-class on
    /metrics, not benchmark-script printouts."""
    global _serve_metrics_cache
    if _serve_metrics_cache is None:
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        _serve_metrics_cache = {
            "ttft": Histogram(
                "rtpu_serve_ttft_s",
                description="Serve time-to-first-token: request submit "
                            "to first sampled token (prefill + splice "
                            "wait)",
                boundaries=[0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                            10.0, 30.0],
                tag_keys=("model",)),
            "tokens": Counter(
                "rtpu_serve_decode_tokens_total",
                description="Decode tokens emitted by the "
                            "continuous-batching engine",
                tag_keys=("model",)),
            "slots": Gauge(
                "rtpu_serve_slots_busy",
                description="Continuous-batching slots currently "
                            "generating",
                tag_keys=("model",)),
            "itl": Histogram(
                "rtpu_serve_itl_s",
                description="Serve inter-token latency: gap between "
                            "consecutive sampled tokens of one stream "
                            "(per decode tick, engine-side)",
                boundaries=[0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                            0.5, 1.0, 5.0],
                tag_keys=("model",)),
        }
    return _serve_metrics_cache


# Per-request token-timestamp ring capacity and the bounded finished-stats
# map: enough stamps to characterize ITL tails without unbounded growth on
# very long generations.
_TOKEN_RING = 2048
_DONE_STATS_MAX = 1024


def bucket_len(n: int, max_len: int, floor: int = 8) -> int:
    """Power-of-2 length bucket (>= floor, <= max_len): THE compile-count
    bound shared by the batch deployment and the engine — one definition
    so the two paths can't drift apart in how many programs they compile."""
    S = floor
    while S < n:
        S <<= 1
    return min(S, max_len)


class ContinuousBatchingEngine:
    """B-slot continuous batching over a shared ragged KV cache."""

    def __init__(self, cfg, params, *, num_slots: int = 4,
                 max_prompt_len: int = 128, max_new_tokens: int = 64,
                 seed: int = 0, model: str = ""):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.generate import KVCache, decode_step, prefill

        self.cfg = cfg
        self.params = params
        self.model = model or "default"
        self._mtags = {"model": self.model}
        self.B = num_slots
        self.max_prompt_len = max_prompt_len
        self.max_new = max_new_tokens
        self.max_len = max_prompt_len + max_new_tokens
        self._jax, self._jnp = jax, jnp

        L = cfg.n_layers
        KVH, hd = cfg.kv_heads, cfg.head_dim
        kv_shape = (L, self.B, self.max_len, KVH, hd)
        self.cache = KVCache(
            k=jnp.zeros(kv_shape, cfg.dtype),
            v=jnp.zeros(kv_shape, cfg.dtype),
            pos=jnp.zeros((self.B,), jnp.int32))
        self.cur_tok = jnp.zeros((self.B,), jnp.int32)

        # Host-side slot bookkeeping (engine lock; the arrays above are
        # replaced wholesale under it).
        self.lock = threading.Lock()
        self.active = [False] * self.B
        self.budget = [0] * self.B      # tokens left to emit per slot
        self.eos = [None] * self.B      # per-request eos id
        self.temp = np.zeros(self.B, np.float32)
        self.out: List[List[int]] = [[] for _ in range(self.B)]
        # Slots recycle; REQUESTS are the stable identity. submit() returns
        # a request id, finished outputs move to _results keyed by it, and
        # readers can never observe a successor request's tokens.
        self.slot_req: List[Optional[int]] = [None] * self.B
        self._req_seq = 0
        self._req_slot: Dict[int, int] = {}
        self._results: Dict[int, List[int]] = {}
        self._done_ev: Dict[int, threading.Event] = {}
        self._discarded: set = set()
        self.failed: Optional[BaseException] = None
        self._free = list(range(self.B))
        self._free_cv = threading.Condition(self.lock)
        # Token timeline (trace plane): per-live-request monotonic token
        # stamps in a bounded ring + per-request TTFT; finished requests
        # fold into a bounded summary map so the final span / ledger row
        # can carry token stats after slot recycling. _stall_flagged makes
        # the stream-stall event exactly-once per request.
        self._token_times: Dict[int, Any] = {}
        self._ttft_vals: Dict[int, float] = {}
        self._token_stats_done: "collections.OrderedDict[int, Dict]" = \
            collections.OrderedDict()
        self._stall_flagged: set = set()
        # Submitters blocked waiting for a slot: the queue-depth signal the
        # serve autoscaler scales decode pools on.
        self._waiting = 0
        self._rng = jax.random.key(seed)
        self._draws = 0

        # ---- compiled units ----
        def _prefill_one(params, tokens, length):
            # [1, S] -> (logits [1, V], k/v [L, 1, S, KVH, hd], pos [1])
            logits, cache = prefill(params, tokens, self.cfg, tokens.shape[1],
                                    lengths=length)
            return logits[0], cache.k[:, 0], cache.v[:, 0]

        self._prefill_one = jax.jit(_prefill_one)

        def _splice(ck, cv, pos, cur, slot_k, slot_v, slot_pos,
                    slot_tok, slot):
            # Insert one request's prefilled K/V + state into slot `slot`.
            ck = jax.lax.dynamic_update_slice(
                ck, slot_k[:, None], (0, slot, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(
                cv, slot_v[:, None], (0, slot, 0, 0, 0))
            pos = pos.at[slot].set(slot_pos)
            cur = cur.at[slot].set(slot_tok)
            return ck, cv, pos, cur

        self._splice = jax.jit(_splice)

        def _tick(params, cache, cur, rng, temps):
            logits, cache = decode_step(params, cache, cur, self.cfg)
            greedy = jnp.argmax(logits, -1).astype(jnp.int32)
            scaled = logits / jnp.maximum(temps[:, None], 1e-6)
            sampled = jax.random.categorical(rng, scaled).astype(jnp.int32)
            nxt = jnp.where(temps <= 0.0, greedy, sampled)
            return nxt, logits, cache

        self._tick = jax.jit(_tick)

    # ------------------------------------------------------------ requests

    def submit(self, tokens, *, max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               timeout: Optional[float] = None,
               arrival_ts: Optional[float] = None,
               queue_wait_s: Optional[float] = None) -> int:
        """Attach a request to a free slot (blocking while all slots busy).
        Returns a stable REQUEST id; poll with peek(), collect with
        result() — valid even after the slot is recycled.

        TTFT accounting measures from request ARRIVAL (queue wait
        included, the signal the serve autoscaler scales on), not from
        prefill start. ``queue_wait_s`` is the time the request already
        spent upstream, accumulated per-host with monotonic clocks
        (serve_context.elapsed_s()); the engine adds its local
        prefill + slot wait monotonically, so cross-machine wall-clock
        skew never touches the histogram. ``arrival_ts`` (epoch seconds)
        is the SAME-PROCESS alternative for embedders/tests; ignored when
        queue_wait_s is given. With neither, arrival is now."""
        jnp = self._jnp
        mono0 = time.monotonic()
        ids = np.asarray(tokens, np.int32)
        if ids.ndim != 1 or ids.size == 0:
            raise ValueError("tokens must be a non-empty 1-D integer list")
        ids = ids[-self.max_prompt_len:]
        S = bucket_len(len(ids), self.max_prompt_len)
        padded = np.zeros((1, S), np.int32)
        padded[0, :len(ids)] = ids
        # Prefill OUTSIDE the engine lock (seconds on first compile).
        from . import trace as serve_trace

        hop = serve_trace.start_hop(
            "serve.prefill", kind="prefill",
            attributes={"model": self.model, "prompt_len": len(ids),
                        "bucket": S, "local": True})
        try:
            logits1, k1, v1 = self._prefill_one(
                self.params, jnp.asarray(padded),
                jnp.asarray([len(ids)], jnp.int32))
        except BaseException as e:
            if hop is not None:
                hop.end(error=type(e).__name__)
            raise
        if hop is not None:
            hop.end()
        # Pad the slot K/V out to the engine max_len on the host once.
        pad = self.max_len - S
        if pad:
            k1 = jnp.pad(k1, ((0, 0), (0, pad), (0, 0), (0, 0)))
            v1 = jnp.pad(v1, ((0, 0), (0, pad), (0, 0), (0, 0)))
        return self._attach(k1, v1, len(ids), np.asarray(logits1),
                            max_new_tokens=max_new_tokens,
                            temperature=temperature, eos_id=eos_id,
                            timeout=timeout, arrival_ts=arrival_ts,
                            queue_wait_s=queue_wait_s, mono0=mono0)

    def attach_prefilled(self, k, v, length: int, logits, *,
                         max_new_tokens: Optional[int] = None,
                         temperature: float = 0.0,
                         eos_id: Optional[int] = None,
                         timeout: Optional[float] = None,
                         arrival_ts: Optional[float] = None,
                         queue_wait_s: Optional[float] = None) -> int:
        """Attach a request whose prefill ran ELSEWHERE — a prefill-pool
        replica's handoff or a prefix-cache hit — splicing the K/V
        straight into a free slot with no prefill compute here.

        ``k``/``v`` are one request's [L, S, KVH, hd] (S = any length
        bucket <= max_len); ``logits`` the prefill's last-position [V]
        row that decides the first token. Everything else matches
        submit()."""
        jnp = self._jnp
        mono0 = time.monotonic()
        k = jnp.asarray(k, self.cfg.dtype)
        v = jnp.asarray(v, self.cfg.dtype)
        if k.ndim != 4 or v.shape != k.shape:
            raise ValueError("k/v must be [L, S, KVH, hd] for one request")
        S = int(k.shape[1])
        length = int(length)
        if not (0 < length <= S <= self.max_len):
            raise ValueError(
                f"bad handoff: length={length} bucket={S} "
                f"max_len={self.max_len}")
        pad = self.max_len - S
        if pad:
            k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
            v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        return self._attach(k, v, length, np.asarray(logits),
                            max_new_tokens=max_new_tokens,
                            temperature=temperature, eos_id=eos_id,
                            timeout=timeout, arrival_ts=arrival_ts,
                            queue_wait_s=queue_wait_s, mono0=mono0)

    def _attach(self, k1, v1, length: int, logits1: np.ndarray, *,
                max_new_tokens: Optional[int], temperature: float,
                eos_id: Optional[int], timeout: Optional[float],
                arrival_ts: Optional[float],
                queue_wait_s: Optional[float] = None,
                mono0: Optional[float] = None) -> int:
        """Shared slot-wait + splice tail of submit()/attach_prefilled():
        k1/v1 are already padded to max_len, logits1 is the host [V] row.
        ``mono0`` is the caller's entry stamp so prefill time counts
        toward TTFT; ``queue_wait_s``/``arrival_ts`` as in submit().

        Trace plane: the engine-attach hop covers this host's slot wait +
        splice (the "engine slot wait" bar of the waterfall); it rides the
        caller thread's serve context, so it nests under the replica span
        automatically."""
        from . import trace as serve_trace

        hop = serve_trace.start_hop(
            "serve.engine_attach", kind="engine",
            attributes={"model": self.model})
        try:
            req = self._attach_locked(
                k1, v1, length, logits1, max_new_tokens=max_new_tokens,
                temperature=temperature, eos_id=eos_id, timeout=timeout,
                arrival_ts=arrival_ts, queue_wait_s=queue_wait_s,
                mono0=mono0, hop=hop)
        except BaseException as e:
            if hop is not None:
                hop.end(error=type(e).__name__)
            raise
        if hop is not None:
            hop.end()
        return req

    def _attach_locked(self, k1, v1, length: int, logits1: np.ndarray, *,
                       max_new_tokens: Optional[int], temperature: float,
                       eos_id: Optional[int], timeout: Optional[float],
                       arrival_ts: Optional[float],
                       queue_wait_s: Optional[float] = None,
                       mono0: Optional[float] = None, hop=None) -> int:
        jnp = self._jnp
        if mono0 is None:
            mono0 = time.monotonic()
        with self._free_cv:
            # One monotonic deadline for the whole wait: contended submits
            # that wake repeatedly must not restart the clock each time.
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            self._waiting += 1
            try:
                while not self._free:
                    # A dead ticker thread recorded the failure and notified
                    # this condition; blocking the full timeout (or forever)
                    # on an engine that will never free a slot helps nobody.
                    if self.failed is not None:
                        raise RuntimeError(
                            f"engine failed: {self.failed!r}")
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError("no free generation slot")
                    self._free_cv.wait(timeout=remaining)
            finally:
                self._waiting -= 1
            if self.failed is not None:
                raise RuntimeError(f"engine failed: {self.failed!r}")
            slot = self._free.pop()
            self._req_seq += 1
            req = self._req_seq
            self.slot_req[slot] = req
            self._req_slot[req] = slot
            self._done_ev[req] = threading.Event()
            # First token comes from the prefill logits, decided under the
            # lock with the slot's sampling config.
            first = self._pick_host(logits1, temperature)
            m = _serve_metrics()
            # Skew-free TTFT: upstream wait is a per-host monotonic
            # accumulation, local wait (prefill + slot) is this host's
            # monotonic delta. The epoch arrival_ts path is same-process
            # only, where wall-clock deltas are safe.
            local_wait = time.monotonic() - mono0
            if queue_wait_s is not None:
                ttft = max(0.0, float(queue_wait_s)) + local_wait
            elif arrival_ts is not None:
                ttft = max(0.0, time.time() - float(arrival_ts))
            else:
                ttft = local_wait
            m["ttft"].observe(ttft, tags=self._mtags)
            m["tokens"].inc(1.0, tags=self._mtags)
            # Token timeline: stamp the first token on this host's
            # monotonic clock; tick() appends one stamp per decode token.
            # Gated on the trace flag so RTPU_SERVE_TRACE=0 keeps the
            # timeline/ITL/stall plane to a single flag check.
            from . import trace as serve_trace

            if serve_trace.enabled():
                self._token_times[req] = collections.deque(
                    [time.monotonic()], maxlen=_TOKEN_RING)
                self._ttft_vals[req] = float(ttft)
            if hop is not None:
                hop.attributes.update(
                    slot=slot, ttft_s=round(float(ttft), 6),
                    slot_wait_s=round(local_wait, 6))
            n = min(max_new_tokens or self.max_new, self.max_new)
            self.active[slot] = True
            self.budget[slot] = n - 1
            self.eos[slot] = eos_id
            self.temp[slot] = temperature
            self.out[slot] = [int(first)]
            ck, cv, pos, cur = self._splice(
                self.cache.k, self.cache.v, self.cache.pos, self.cur_tok,
                k1, v1, jnp.asarray(length, jnp.int32),
                jnp.asarray(int(first), jnp.int32), slot)
            from ray_tpu.models.generate import KVCache

            self.cache = KVCache(k=ck, v=cv, pos=pos)
            self.cur_tok = cur
            if self.budget[slot] <= 0 or (eos_id is not None
                                          and int(first) == eos_id):
                self._retire_locked(slot)
            m["slots"].set(self.B - len(self._free), tags=self._mtags)
            return req

    def prefill_only(self, tokens):
        """Run this engine's bucketed prefill WITHOUT taking a slot:
        returns host ``(k, v, length, logits)`` with k/v [L, S, KVH, hd]
        (S = the length bucket) — exactly the handoff blob
        attach_prefilled() accepts. The prefill pool and the prefix cache
        both speak this format."""
        jnp = self._jnp
        ids = np.asarray(tokens, np.int32)
        if ids.ndim != 1 or ids.size == 0:
            raise ValueError("tokens must be a non-empty 1-D integer list")
        ids = ids[-self.max_prompt_len:]
        S = bucket_len(len(ids), self.max_prompt_len)
        padded = np.zeros((1, S), np.int32)
        padded[0, :len(ids)] = ids
        logits1, k1, v1 = self._prefill_one(
            self.params, jnp.asarray(padded),
            jnp.asarray([len(ids)], jnp.int32))
        return (np.asarray(k1), np.asarray(v1), len(ids),
                np.asarray(logits1))

    def stats(self) -> Dict[str, float]:
        """Load snapshot for the serve controller's signal poll: busy/total
        slots, occupancy in [0,1], and submitters blocked on a slot."""
        with self.lock:
            busy = self.B - len(self._free)
            return {"slots_busy": float(busy),
                    "slots_total": float(self.B),
                    "occupancy": busy / float(self.B),
                    "queued": float(self._waiting)}

    def _pick_host(self, logits: np.ndarray, temperature: float) -> int:
        if temperature <= 0:
            return int(np.argmax(logits))
        jax = self._jax
        self._draws += 1
        key = jax.random.fold_in(self._rng, self._draws)
        return int(jax.random.categorical(
            key, self._jnp.asarray(logits) / max(temperature, 1e-6)))

    def _summarize_locked(self, req: int, *, cause: str = "") -> None:
        """Fold a request's token ring into the bounded finished-stats
        map (called at retirement, under the engine lock) so the final
        stream span / ledger row can read token counts + ITL percentiles
        after the slot recycles."""
        dq = self._token_times.pop(req, None)
        ttft = self._ttft_vals.pop(req, None)
        self._stall_flagged.discard(req)
        if dq is None:
            return
        stamps = list(dq)
        itls = [b - a for a, b in zip(stamps, stamps[1:])]
        slot = self._req_slot.get(req)
        tokens = len(self.out[slot]) if slot is not None else len(stamps)
        st: Dict[str, Any] = {"tokens": tokens, "ttft_s": ttft,
                              "abort_cause": cause}
        if itls:
            srt = sorted(itls)
            st.update(
                itl_mean_s=sum(itls) / len(itls),
                itl_p50_s=srt[len(srt) // 2],
                itl_p99_s=srt[min(len(srt) - 1, int(len(srt) * 0.99))],
                itl_max_s=srt[-1])
        self._token_stats_done[req] = st
        while len(self._token_stats_done) > _DONE_STATS_MAX:
            self._token_stats_done.popitem(last=False)

    def _retire_locked(self, slot: int) -> None:
        self.active[slot] = False
        req = self.slot_req[slot]
        if req is not None:
            self._summarize_locked(
                req, cause="discarded" if req in self._discarded else "")
            if req in self._discarded:
                # Consumer went away mid-stream: drop the output instead
                # of storing it for a reader that will never come.
                self._discarded.discard(req)
                self._done_ev.pop(req, None)
            else:
                self._results[req] = list(self.out[slot])
                self._done_ev[req].set()
            self._req_slot.pop(req, None)
            self.slot_req[slot] = None
        self._free.append(slot)
        self._free_cv.notify_all()

    def discard(self, req: int) -> None:
        """Consumer abandoned the request (client disconnect): release its
        stored output now, or mark it to be dropped at retirement — either
        way no per-request state outlives the reader."""
        with self.lock:
            if req in self._results or (req in self._done_ev
                                        and req not in self._req_slot):
                self._results.pop(req, None)
                self._done_ev.pop(req, None)
                return
            slot = self._req_slot.get(req)
            if slot is not None:
                self._discarded.add(req)
                self.budget[slot] = 0  # retire at the next tick

    def abort(self, req: int) -> bool:
        """Cancel a request NOW, between engine steps: the slot (and its
        KV rows) frees immediately under the engine lock and any stored
        output is dropped. Unlike discard(), which lets the slot retire at
        the NEXT tick, abort is the disconnect path's guarantee that
        capacity frees within one step. Returns True if the request was
        known (live or finished), False for an unknown/already-released
        id — callers treat double-abort as a no-op."""
        with self.lock:
            slot = self._req_slot.get(req)
            if slot is not None:
                # Summarize FIRST with the abort cause: _retire_locked's
                # own summarize is then a no-op (ring already folded).
                self._summarize_locked(req, cause="aborted")
                self._discarded.add(req)
                self._retire_locked(slot)
                _serve_metrics()["slots"].set(
                    self.B - len(self._free), tags=self._mtags)
                return True
            if req in self._results or req in self._done_ev:
                self._results.pop(req, None)
                self._done_ev.pop(req, None)
                return True
            return False

    # ---------------------------------------------------------------- tick

    def tick(self) -> int:
        """One decode step for every active slot; returns #active after.

        The whole tick holds the engine lock: a snapshot-compute-swap
        design would let a submit() splice land between snapshot and swap
        and be ERASED by the swap. submit's slow part (prefill compile/run)
        is outside the lock, so attaches wait at most one tick for the
        fast splice. Inactive slots compute garbage rows (their pos keeps
        advancing; writes clamp harmlessly) — the price of one compiled
        program; a splice fully re-initializes a slot on attach."""
        jax, jnp = self._jax, self._jnp
        with self.lock:
            if not any(self.active):
                return 0
            self._draws += 1
            key = jax.random.fold_in(self._rng, self._draws)
            temps = jnp.asarray(self.temp)
            nxt, logits, cache = self._tick(
                self.params, self.cache, self.cur_tok, key, temps)
            nxt_host = np.asarray(nxt)
            self.cache = cache
            self.cur_tok = nxt
            emitted = 0
            now_m = time.monotonic()
            m_itl = _serve_metrics()["itl"]
            for s in range(self.B):
                if not self.active[s]:
                    continue
                tok = int(nxt_host[s])
                self.out[s].append(tok)
                # Token timeline: one monotonic stamp per emitted token
                # feeds the ITL histogram and the stream-stall detector.
                dq = self._token_times.get(self.slot_req[s])
                if dq is not None:
                    m_itl.observe(now_m - dq[-1], tags=self._mtags)
                    dq.append(now_m)
                emitted += 1
                self.budget[s] -= 1
                if self.budget[s] <= 0 or (self.eos[s] is not None
                                           and tok == self.eos[s]):
                    self._retire_locked(s)
            if emitted:
                m = _serve_metrics()
                m["tokens"].inc(float(emitted), tags=self._mtags)
                m["slots"].set(self.B - len(self._free), tags=self._mtags)
            return sum(self.active)

    # ------------------------------------------------------------- results

    def result(self, req: int, timeout: Optional[float] = None) -> List[int]:
        """Block until the request finished; returns its tokens. The
        result stays retrievable (and peek-able) after slot recycling;
        pop_result() releases it."""
        ev = self._done_ev.get(req)
        if ev is None:
            raise KeyError(f"unknown request {req}")
        if not ev.wait(timeout=timeout):
            raise TimeoutError(f"request {req} still generating")
        with self.lock:
            if self.failed is not None and req not in self._results:
                raise RuntimeError(
                    f"generation engine failed: {self.failed!r}")
            return list(self._results[req])

    def pop_result(self, req: int) -> List[int]:
        """result() + release the stored output (bounds memory for
        long-running engines)."""
        out = self.result(req)
        with self.lock:
            self._results.pop(req, None)
            self._done_ev.pop(req, None)
        return out

    def is_done(self, req: int) -> bool:
        ev = self._done_ev.get(req)
        return ev is not None and ev.is_set()

    def check_failed(self) -> Optional[BaseException]:
        return self.failed

    def peek(self, req: int) -> List[int]:
        """Tokens emitted so far (streaming consumers poll this).

        The stream-stall detector lives here rather than in the ticker:
        a hung tick thread (the main way a stream stalls) can't run its
        own watchdog, but the consumer polling peek() is alive by
        definition — it notices the silence and fires the exactly-once
        STREAM_STALLED event with a stack capture of every thread."""
        stalled_age = None
        with self.lock:
            done = self._results.get(req)
            if done is not None:
                return list(done)
            slot = self._req_slot.get(req)
            if slot is None:
                raise KeyError(f"unknown request {req}")
            out = list(self.out[slot])
            dq = self._token_times.get(req)
            if dq is not None and req not in self._stall_flagged:
                from ray_tpu import flags

                stall_s = float(flags.get("RTPU_SERVE_STALL_S") or 0.0)
                if stall_s > 0:
                    age = time.monotonic() - dq[-1]
                    if age > stall_s:
                        self._stall_flagged.add(req)
                        stalled_age = age
        if stalled_age is not None:
            self._emit_stall(req, stalled_age)
        return out

    def _emit_stall(self, req: int, age_s: float) -> None:
        """Ship the STREAM_STALLED cluster event (outside the engine lock:
        the stack capture walks every thread's frames)."""
        from ray_tpu.core import events
        from . import context as serve_context
        from . import trace as serve_trace

        rid = serve_context.get_request_id()
        try:
            events.emit(
                "WARNING", "STREAM_STALLED",
                f"stream {rid or req} on model {self.model} emitted no "
                f"token for {age_s:.1f}s with a live slot",
                source="serve",
                data={"stack": serve_trace.capture_stacks(),
                      "request_id": rid, "engine_req": req,
                      "model": self.model, "age_s": round(age_s, 3)})
        except Exception:
            pass

    # ------------------------------------------------------- token stats

    def token_stats(self, req: int) -> Optional[Dict[str, Any]]:
        """Per-request token timeline summary: token count, TTFT, ITL
        mean/p50/p99/max, abort cause. Live requests get an in-flight
        summary; finished ones read the bounded done-map (so the final
        stream span can attach stats AFTER the slot recycled — call this
        BEFORE abort() on cleanup paths, which records cause=aborted)."""
        with self.lock:
            st = self._token_stats_done.get(req)
            if st is not None:
                return dict(st)
            dq = self._token_times.get(req)
            if dq is None:
                return None
            stamps = list(dq)
            itls = [b - a for a, b in zip(stamps, stamps[1:])]
            slot = self._req_slot.get(req)
            out: Dict[str, Any] = {
                "tokens": len(self.out[slot]) if slot is not None
                else len(stamps),
                "ttft_s": self._ttft_vals.get(req), "abort_cause": ""}
            if itls:
                srt = sorted(itls)
                out.update(
                    itl_mean_s=sum(itls) / len(itls),
                    itl_p50_s=srt[len(srt) // 2],
                    itl_p99_s=srt[min(len(srt) - 1,
                                      int(len(srt) * 0.99))],
                    itl_max_s=srt[-1])
            return out

    def last_token_age(self, req: int) -> Optional[float]:
        """Seconds since the request's newest token (monotonic), None for
        unknown/finished requests — the stall detector's raw signal."""
        with self.lock:
            dq = self._token_times.get(req)
            if dq is None:
                return None
            return time.monotonic() - dq[-1]

    # ------------------------------------------------------- driver thread

    def run_forever(self, stop: threading.Event, idle_sleep: float = 0.005):
        """Tick loop for a background thread: ticks while any slot is
        active, sleeps briefly when idle."""
        import time

        while not stop.is_set():
            try:
                n = self.tick()
            except BaseException as e:  # device/runtime failure
                # A dead ticker must not strand pollers: record the
                # failure, wake every waiter, and stop. is_done()/result()
                # surface the error instead of hanging forever.
                with self.lock:
                    self.failed = e
                    for ev in self._done_ev.values():
                        ev.set()
                    self._free_cv.notify_all()
                return
            if n == 0:
                time.sleep(idle_sleep)
