"""Per-request serve context: the deadline that rides the whole path.

Parity: reference serve/context.py (_serve_request_context ContextVar
carrying request id + deadline). The proxy (or a handle's
``.options(deadline_s=...)``) stamps an ABSOLUTE wall-clock deadline;
every downstream hop — router assign, replica execution, @serve.batch
seal, llm_engine slot wait — reads it from here, so nested handle
composition inherits the caller's budget without threading kwargs
through user code.
"""
from __future__ import annotations

import contextvars
import time
from typing import Optional

_request_ctx: "contextvars.ContextVar[Optional[dict]]" = (
    contextvars.ContextVar("serve_request_ctx", default=None))


def set_request_context(*, deadline_ts: Optional[float] = None,
                        request_id: str = "",
                        start_ts: Optional[float] = None):
    """Install the current request's context; returns a reset token.
    ``start_ts`` (epoch seconds) is when the request entered the system —
    stamped once at the outermost hop and inherited by nested handle
    calls, so TTFT accounting includes every queue the request crossed."""
    return _request_ctx.set(
        {"deadline_ts": deadline_ts, "request_id": request_id,
         "start_ts": start_ts})


def reset_request_context(token) -> None:
    _request_ctx.reset(token)


def get_request_context() -> Optional[dict]:
    return _request_ctx.get()


def get_request_deadline() -> Optional[float]:
    """Absolute (epoch-seconds) deadline of the active request, or None."""
    c = _request_ctx.get()
    return c.get("deadline_ts") if c else None


def get_request_start() -> Optional[float]:
    """Epoch-seconds arrival time of the active request, or None."""
    c = _request_ctx.get()
    return c.get("start_ts") if c else None


def remaining_s(default: Optional[float] = None) -> Optional[float]:
    """Seconds left on the active request's deadline. Expired requests
    return 0.0 (never negative); no deadline returns ``default``."""
    dl = get_request_deadline()
    if dl is None:
        return default
    return max(0.0, dl - time.time())


def expired() -> bool:
    dl = get_request_deadline()
    return dl is not None and time.time() > dl
