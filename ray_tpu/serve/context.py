"""Per-request serve context: the deadline that rides the whole path.

Parity: reference serve/context.py (_serve_request_context ContextVar
carrying request id + deadline). The proxy (or a handle's
``.options(deadline_s=...)``) stamps an ABSOLUTE wall-clock deadline;
every downstream hop — router assign, replica execution, @serve.batch
seal, llm_engine slot wait — reads it from here, so nested handle
composition inherits the caller's budget without threading kwargs
through user code.
"""
from __future__ import annotations

import contextvars
import time
from typing import Optional

_request_ctx: "contextvars.ContextVar[Optional[dict]]" = (
    contextvars.ContextVar("serve_request_ctx", default=None))


def set_request_context(*, deadline_ts: Optional[float] = None,
                        request_id: str = "",
                        start_ts: Optional[float] = None,
                        queue_wait_s: float = 0.0,
                        trace_ctx: Optional[dict] = None):
    """Install the current request's context; returns a reset token.
    ``start_ts`` (epoch seconds) is when the request entered the system —
    stamped once at the outermost hop and inherited by nested handle
    calls, so TTFT accounting includes every queue the request crossed.

    ``queue_wait_s`` is the time the request had already spent upstream,
    accumulated hop by hop with each host's OWN monotonic clock (the
    router adds its local dwell before forwarding). Latency accounting
    (TTFT) uses queue_wait_s plus the locally-stamped ``arrival_mono``
    delta — never a cross-host epoch difference, which wall-clock skew
    between machines would bias (or clamp to zero).

    ``trace_ctx`` is the serving trace plane's wire context (serve/
    trace.py): {"traceparent", "request_id", "deployment"}. The
    traceparent rides this same dict as ``trace_id``/``parent_span_id``,
    so nested handle calls and the disagg prefill→decode hop inherit one
    trace_id without threading kwargs through user code."""
    c = {"deadline_ts": deadline_ts, "request_id": request_id,
         "start_ts": start_ts,
         "queue_wait_s": max(0.0, float(queue_wait_s or 0.0)),
         "arrival_mono": time.monotonic()}
    if trace_ctx:
        tp = trace_ctx.get("traceparent") or ""
        try:
            from ray_tpu.util.tracing import SpanContext

            sc = SpanContext.from_traceparent(tp)
        except Exception:
            sc = None
        if sc is not None:
            c["trace_id"] = sc.trace_id
            c["parent_span_id"] = sc.span_id
        if not c["request_id"]:
            c["request_id"] = trace_ctx.get("request_id") or ""
        if trace_ctx.get("deployment"):
            c["deployment"] = trace_ctx["deployment"]
    return _request_ctx.set(c)


def reset_request_context(token) -> None:
    _request_ctx.reset(token)


def get_request_context() -> Optional[dict]:
    return _request_ctx.get()


def get_request_id() -> str:
    """Request id of the active request ("" when none installed)."""
    c = _request_ctx.get()
    return (c.get("request_id") or "") if c else ""


def get_request_deadline() -> Optional[float]:
    """Absolute (epoch-seconds) deadline of the active request, or None."""
    c = _request_ctx.get()
    return c.get("deadline_ts") if c else None


def get_request_start() -> Optional[float]:
    """Epoch-seconds arrival time of the active request, or None.
    Informational (logs, deadline math on one host); latency deltas
    should use :func:`elapsed_s`, which is skew-free across hosts."""
    c = _request_ctx.get()
    return c.get("start_ts") if c else None


def elapsed_s() -> Optional[float]:
    """Seconds the active request has spent in the system so far:
    upstream queue wait (accumulated per-host, monotonic) plus the time
    since it arrived on THIS host. None when no request context is
    installed. Immune to wall-clock skew between machines — feed this
    (not epoch deltas) into TTFT/latency instruments."""
    c = _request_ctx.get()
    if c is None:
        return None
    return (c.get("queue_wait_s", 0.0)
            + max(0.0, time.monotonic() - c["arrival_mono"]))


def remaining_s(default: Optional[float] = None) -> Optional[float]:
    """Seconds left on the active request's deadline. Expired requests
    return 0.0 (never negative); no deadline returns ``default``."""
    dl = get_request_deadline()
    if dl is None:
        return default
    return max(0.0, dl - time.time())


def expired() -> bool:
    dl = get_request_deadline()
    return dl is not None and time.time() > dl
