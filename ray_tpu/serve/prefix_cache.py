"""KV prefix cache: hot prompts keep their prefilled K/V resident.

Parity: vLLM automatic prefix caching / SGLang RadixAttention, adapted to
the disaggregated plane (disagg.py): decode replicas cache the handoff
blob — ``(k, v, length, logits)`` exactly as attach_prefilled() accepts
it — keyed by a hash of the prompt tokens, LRU-evicted by KV BYTES (the
resource that actually runs out), so a repeated system prompt never pays
prefill again anywhere.

Two layers:
- ``PrefixCache``: per-replica store (this module's hot path; pure host
  numpy, no JAX). Flag-gated by RTPU_PREFIX_CACHE so the disabled path
  is uniform no-ops at every call site.
- ``PrefixIndex``: controller-side cluster index mapping prefix hash ->
  holder replicas + cluster-wide hit counts, fed by the controller's
  replica stats poll. It derives (a) the hot-prefix routing table pushed
  to routers so requests steer to replicas already holding their prefix,
  and (b) promotion decisions: once a prefix is cluster-hot, replicas
  that miss it pull the blob straight from a holder (worker<->worker,
  bytes never transit the controller).
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from ray_tpu import flags

_cache_metrics_cache = None


def _cache_metrics():
    """Lazy shared prefix-cache metrics (one set per process, model tag)."""
    global _cache_metrics_cache
    if _cache_metrics_cache is None:
        from ray_tpu.util.metrics import Counter, Gauge

        _cache_metrics_cache = {
            "hits": Counter(
                "rtpu_prefix_cache_hits_total",
                description="Prefix-cache hits: requests whose prefilled "
                            "K/V was already resident (prefill skipped)",
                tag_keys=("model",)),
            "misses": Counter(
                "rtpu_prefix_cache_misses_total",
                description="Prefix-cache misses: requests that had to "
                            "run (or wait for) a cold prefill",
                tag_keys=("model",)),
            "bytes": Gauge(
                "rtpu_prefix_cache_bytes",
                description="Resident prefix-cache K/V bytes on this "
                            "replica (LRU evicts past the budget)",
                tag_keys=("model",)),
            "entries": Gauge(
                "rtpu_prefix_cache_entries",
                description="Resident prefix-cache entries on this "
                            "replica",
                tag_keys=("model",)),
        }
    return _cache_metrics_cache


def prefix_key(tokens) -> str:
    """Stable hash of a token sequence: the cache/index/routing key.

    Exact-prompt keying (not per-block): a hit means THE WHOLE prefill is
    skippable, which is the common win for repeated system prompts."""
    ids = np.ascontiguousarray(np.asarray(tokens, np.int32))
    return hashlib.blake2b(ids.tobytes(), digest_size=16).hexdigest()


class PrefixEntry:
    """One cached prefill handoff blob (host numpy, ready to splice)."""

    __slots__ = ("k", "v", "length", "logits", "nbytes", "hits")

    def __init__(self, k: np.ndarray, v: np.ndarray, length: int,
                 logits: np.ndarray):
        self.k = k
        self.v = v
        self.length = int(length)
        self.logits = logits
        self.nbytes = int(k.nbytes + v.nbytes + logits.nbytes)
        self.hits = 0


class PrefixCache:
    """Per-replica LRU-by-bytes store of prefilled K/V blobs."""

    def __init__(self, *, max_bytes: Optional[int] = None, model: str = ""):
        if max_bytes is None:
            max_bytes = int(flags.get("RTPU_PREFIX_CACHE_MAX_MB") * 2**20)
        self.max_bytes = max_bytes
        self.model = model or "default"
        self._mtags = {"model": self.model}
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, PrefixEntry]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    @property
    def enabled(self) -> bool:
        return bool(flags.get("RTPU_PREFIX_CACHE"))

    def get(self, h: str) -> Optional[PrefixEntry]:
        """Lookup + LRU touch; counts the hit/miss (the autoscaler and
        BENCH read hit rate from these counters)."""
        if not self.enabled:
            return None
        m = _cache_metrics()
        with self._lock:
            e = self._entries.get(h)
            if e is None:
                self.misses += 1
                m["misses"].inc(1.0, tags=self._mtags)
                return None
            self._entries.move_to_end(h)
            e.hits += 1
            self.hits += 1
        m["hits"].inc(1.0, tags=self._mtags)
        return e

    def put(self, h: str, k, v, length: int, logits) -> bool:
        """Insert a blob (host copies); evicts LRU entries past the byte
        budget. Oversized blobs (> budget) are refused rather than
        wiping the whole cache for one entry."""
        if not self.enabled:
            return False
        e = PrefixEntry(np.asarray(k), np.asarray(v), length,
                        np.asarray(logits))
        if e.nbytes > self.max_bytes:
            return False
        with self._lock:
            old = self._entries.pop(h, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[h] = e
            self._bytes += e.nbytes
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
            m = _cache_metrics()
            m["bytes"].set(float(self._bytes), tags=self._mtags)
            m["entries"].set(float(len(self._entries)), tags=self._mtags)
        return True

    def __contains__(self, h: str) -> bool:
        with self._lock:
            return h in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def export(self, h: str) -> Optional[Dict[str, Any]]:
        """Serializable form of one entry for cross-replica promotion
        (the missing replica pulls this straight from a holder)."""
        with self._lock:
            e = self._entries.get(h)
            if e is None:
                return None
            return {"k": e.k, "v": e.v, "length": e.length,
                    "logits": e.logits}

    def insert_blob(self, h: str, blob: Dict[str, Any]) -> bool:
        return self.put(h, blob["k"], blob["v"], blob["length"],
                        blob["logits"])

    def stats(self, *, top: int = 64) -> Dict[str, Any]:
        """Snapshot for serve_stats(): counters, residency, and the
        hottest resident hashes with per-entry hit counts — the
        controller's poll feeds these into the cluster PrefixIndex."""
        with self._lock:
            hot = sorted(((h, e.hits) for h, e in self._entries.items()),
                         key=lambda kv: -kv[1])[:top]
            return {"hits": self.hits, "misses": self.misses,
                    "bytes": self._bytes, "entries": len(self._entries),
                    "holders": [h for h, _ in hot],
                    "hot": dict(hot)}


class PrefixIndex:
    """Cluster view (lives in the ServeController): which replicas hold
    which prefixes, and how hot each prefix is cluster-wide.

    Thread-safe: the controller's control-loop thread mutates it
    (update_replica/drop_replica per stats poll) while routing queries
    (routes(), via get_routing_config) arrive on the actor's request
    threads — every method snapshots or mutates under one lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_replica: Dict[str, Dict[str, int]] = {}  # rid -> {h: hits}
        self._holders: Dict[str, Set[str]] = {}           # h -> {rid}
        self._promoted: Set[Tuple[str, str]] = set()      # (h, target_rid)

    def update_replica(self, rid: str, holders: List[str],
                       hot: Dict[str, int]) -> None:
        """Fold one replica's stats-poll report into the index. Reports
        are cumulative per replica; cluster hits = sum of latest reports."""
        with self._lock:
            self._by_replica[rid] = {h: int(hot.get(h, 0)) for h in holders}
            self._rebuild_locked()

    def drop_replica(self, rid: str) -> None:
        with self._lock:
            if self._by_replica.pop(rid, None) is not None:
                self._rebuild_locked()

    def _rebuild_locked(self) -> None:
        holders: Dict[str, Set[str]] = {}
        for rid, held in self._by_replica.items():
            for h in held:
                holders.setdefault(h, set()).add(rid)
        self._holders = holders

    def replica_ids(self) -> List[str]:
        with self._lock:
            return list(self._by_replica)

    def holders(self, h: str) -> Set[str]:
        with self._lock:
            return set(self._holders.get(h, ()))

    def _cluster_hits_locked(self, h: str) -> int:
        return sum(held.get(h, 0) for held in self._by_replica.values())

    def cluster_hits(self, h: str) -> int:
        with self._lock:
            return self._cluster_hits_locked(h)

    def routes(self, *, top: int = 128) -> Dict[str, List[str]]:
        """Hot-prefix routing table for get_routing_config(): hash ->
        sorted holder replica ids, hottest prefixes first."""
        with self._lock:
            scored = sorted(self._holders,
                            key=lambda h: -self._cluster_hits_locked(h))[:top]
            return {h: sorted(self._holders[h]) for h in scored}

    def promotions(self, all_replicas: List[str],
                   *, threshold: Optional[int] = None
                   ) -> List[Tuple[str, str, str]]:
        """(prefix, holder_rid, target_rid) pulls to run: cluster-hot
        prefixes broadcast to replicas that don't hold them yet. Each
        (prefix, target) pair promotes at most once per index lifetime —
        a replica that joins later still receives earlier hot prefixes,
        but the broadcast never repeats on every control tick."""
        if threshold is None:
            threshold = int(flags.get("RTPU_PREFIX_CACHE_PROMOTE_HITS"))
        if threshold <= 0 or not flags.get("RTPU_PREFIX_CACHE"):
            return []
        out: List[Tuple[str, str, str]] = []
        with self._lock:
            for h, holders in self._holders.items():
                if not holders or self._cluster_hits_locked(h) < threshold:
                    continue
                holder = sorted(holders)[0]
                for t in all_replicas:
                    if t in holders or (h, t) in self._promoted:
                        continue
                    out.append((h, holder, t))
                    self._promoted.add((h, t))
        return out
