"""gRPC ingress for serve deployments.

Parity: reference serve gRPC proxy (serve/_private/proxy.py gRPCProxy —
user-schema gRPC ingress alongside HTTP). This implementation uses gRPC's
generic handler with a JSON-over-bytes envelope instead of per-app protoc
stubs: method /rtpu.serve/Call takes {"route": "/prefix", "input": ...} and
returns {"result": ...}; /rtpu.serve/CallStream is the server-streaming
variant for stream=True deployments (one JSON message per yielded item).
Routing, replica choice, and multiplexing all ride the same DeploymentHandle
path as HTTP and Python callers.
"""
from __future__ import annotations

import json
from concurrent import futures
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu import flags
from ray_tpu.core.controller import DeadlineExceededError

from . import trace
from .admission import BackPressureError
from .controller import CONTROLLER_NAME
from .handle import DeploymentHandle


def _envelope_timeout_s(request) -> float:
    """Per-request budget from the JSON envelope's timeout_s field, else
    the RTPU_SERVE_REQUEST_TIMEOUT_S flag default (the fix for the old
    hard-coded 60s)."""
    try:
        v = float(request.get("timeout_s") or 0)
        if v > 0:
            return v
    except (TypeError, ValueError):
        pass
    return float(flags.get("RTPU_SERVE_REQUEST_TIMEOUT_S"))


def _ingress_request_id(request) -> str:
    """Ingress stamping (the HTTP proxy's X-Request-Id analog): honor the
    envelope's request_id when the client sent one, else mint one HERE —
    ledger rows and cancellation events must never carry an empty id."""
    rid = request.get("request_id")
    if isinstance(rid, str) and rid:
        return rid
    return trace.new_request_id()


def _ser(obj) -> bytes:
    return json.dumps(obj).encode()


def _de(data: bytes):
    return json.loads(data.decode()) if data else {}


class GRPCProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 9000):
        self.host = host
        self.port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        self._routes: Dict[str, Dict[str, Any]] = {}
        self._server = None

    # ----------------------------------------------------------------- serve

    def start(self) -> None:
        import grpc

        proxy = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method == "/rtpu.serve/Call":
                    return grpc.unary_unary_rpc_method_handler(
                        proxy._call,
                        request_deserializer=_de,
                        response_serializer=_ser,
                    )
                if handler_call_details.method == "/rtpu.serve/CallStream":
                    return grpc.unary_stream_rpc_method_handler(
                        proxy._call_stream,
                        request_deserializer=_de,
                        response_serializer=_ser,
                    )
                return None

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16,
                                       thread_name_prefix="grpc-proxy"))
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        self._server.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1)

    # --------------------------------------------------------------- routing

    def _refresh_routes(self) -> None:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
        self._routes = ray_tpu.get(ctrl.get_route_info.remote())

    def _resolve(self, route: str) -> Optional[Dict[str, Any]]:
        info = self._routes.get(route)
        if info is None:
            self._refresh_routes()
            info = self._routes.get(route)
        return info

    def _handle_for(self, request):
        route = request.get("route")
        info = self._resolve(route or "")
        if info is None:
            raise KeyError(f"no deployment at route {route!r}")
        handle = self._handles.setdefault(
            info["name"], DeploymentHandle(info["name"]))
        if request.get("multiplexed_model_id"):
            handle = handle.options(
                multiplexed_model_id=request["multiplexed_model_id"])
        return handle, info

    def _call(self, request, context):
        import grpc

        rid = _ingress_request_id(request)
        root = None
        try:
            context.send_initial_metadata((("x-request-id", rid),))
        except Exception:
            pass  # metadata already sent / test doubles without support
        try:
            handle, info = self._handle_for(request)
            root = trace.start_request(request_id=rid,
                                       deployment=info["name"],
                                       proto="grpc", method="Call")
            result = handle.options(
                deadline_s=_envelope_timeout_s(request), request_id=rid,
                trace_ctx=root.trace_ctx if root is not None else None,
            ).remote(request.get("input")).result()
            if root is not None:
                root.finish("ok")
            return {"result": result}
        except BackPressureError as e:
            if root is not None:
                root.finish("shed", error=str(e))
            context.set_trailing_metadata(
                (("retry-after-s", f"{e.retry_after_s:g}"),))
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except DeadlineExceededError as e:
            if root is not None:
                root.finish("deadline", error=str(e))
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        except Exception as e:
            if root is not None:
                root.finish("error", error=str(e))
            context.abort(grpc.StatusCode.INTERNAL, str(e))

    def _call_stream(self, request, context):
        import grpc

        rid = _ingress_request_id(request)
        root = None
        try:
            context.send_initial_metadata((("x-request-id", rid),))
        except Exception:
            pass
        stream = None
        items = 0
        try:
            handle, info = self._handle_for(request)
            root = trace.start_request(request_id=rid,
                                       deployment=info["name"],
                                       proto="grpc", method="CallStream")
            stream = iter(handle.options(
                stream=True,
                deadline_s=_envelope_timeout_s(request), request_id=rid,
                trace_ctx=root.trace_ctx if root is not None else None,
            ).remote(request.get("input")))
            for item in stream:
                if not context.is_active():
                    # Client went away mid-stream: stop pulling; the
                    # finally's close() aborts the replica generator and
                    # frees its engine slot now.
                    if root is not None:
                        root.finish("cancelled", items=items)
                    return
                items += 1
                yield {"item": item}
            if root is not None:
                root.finish("ok", items=items)
        except BackPressureError as e:
            if root is not None:
                root.finish("shed", error=str(e), items=items)
            context.set_trailing_metadata(
                (("retry-after-s", f"{e.retry_after_s:g}"),))
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except DeadlineExceededError as e:
            if root is not None:
                root.finish("deadline", error=str(e), items=items)
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        except Exception as e:
            if root is not None:
                root.finish("error", error=str(e), items=items)
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        finally:
            if root is not None:
                # GeneratorExit (client hangup) skips every except arm;
                # first finish wins, so this is a no-op on normal paths.
                root.finish("cancelled", items=items)
            close = getattr(stream, "close", None)
            if close is not None:
                close()
