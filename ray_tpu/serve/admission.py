"""Admission control for the serve router: bounded queues, per-replica
circuit breakers, and a retry budget.

Parity: reference Serve's ``max_queued_requests`` (handle-side queue bound
shedding with BackPressureError → HTTP 503), combined with the classic
SRE overload pattern pair: a consecutive-failure circuit breaker per
replica (open → cooldown → half-open probe) that the power-of-two picker
skips, and a token-bucket retry budget capped as a fraction of admitted
traffic so retries cannot amplify an outage. Everything here is gated by
``RTPU_SERVE_ADMISSION`` — disabled, the request path pays exactly one
flag check and behaves like the legacy unbounded router.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ray_tpu import flags


class BackPressureError(Exception):
    """The deployment's queue bound (max_queued_requests) is exhausted —
    the request was shed WITHOUT executing. Carries ``retry_after_s`` for
    the proxy's Retry-After header."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


_metrics_cache = None
_metrics_lock = threading.Lock()


def serve_metrics():
    """Lazy shared overload-protection instruments (util/metrics plane)."""
    global _metrics_cache
    if _metrics_cache is None:
        with _metrics_lock:
            if _metrics_cache is None:
                from ray_tpu.util.metrics import Counter, Gauge

                _metrics_cache = {
                    "shed": Counter(
                        "rtpu_serve_shed_total",
                        description="Requests shed by serve admission "
                                    "control before executing, by reason "
                                    "(queue_full, breaker_open, expired)",
                        tag_keys=("deployment", "reason")),
                    "deadline": Counter(
                        "rtpu_serve_deadline_exceeded_total",
                        description="Serve requests dropped because their "
                                    "end-to-end deadline passed at a queue "
                                    "boundary or mid-execution",
                        tag_keys=("deployment",)),
                    "cancelled": Counter(
                        "rtpu_serve_cancelled_total",
                        description="Serve requests cancelled by the "
                                    "client (disconnect / explicit cancel) "
                                    "before completing",
                        tag_keys=("deployment",)),
                    "breaker": Gauge(
                        "rtpu_serve_breaker_open",
                        description="Per-deployment count of replica "
                                    "circuit breakers currently open "
                                    "(consecutive-failure trip; half-open "
                                    "probes still count as open)",
                        tag_keys=("deployment",)),
                }
    return _metrics_cache


class CircuitBreaker:
    """Per-replica consecutive-failure breaker.

    closed → (threshold consecutive failures) → open → (cooldown) →
    half-open: ONE probe request passes; its success closes the breaker,
    its failure re-opens with a fresh cooldown.
    """

    def __init__(self, threshold: int, cooldown_s: float):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.failures = 0
        self.state = "closed"
        self.opened_at = 0.0
        self._probe_inflight = False

    def allow(self, now: Optional[float] = None) -> bool:
        """May the router send this replica a request right now?"""
        if self.state == "closed":
            return True
        now = time.time() if now is None else now
        if now - self.opened_at >= self.cooldown_s and not self._probe_inflight:
            # Half-open: exactly one probe at a time.
            self.state = "half_open"
            self._probe_inflight = True
            return True
        return False

    def on_success(self) -> bool:
        """Returns True when this success CLOSED an open breaker."""
        was_open = self.state != "closed"
        self.failures = 0
        self.state = "closed"
        self._probe_inflight = False
        return was_open

    def on_failure(self, now: Optional[float] = None) -> bool:
        """Returns True when this failure TRIPPED the breaker open."""
        now = time.time() if now is None else now
        self.failures += 1
        self._probe_inflight = False
        if self.state == "half_open":
            # Failed probe: straight back to open, fresh cooldown.
            self.state = "open"
            self.opened_at = now
            return False
        if self.state == "closed" and self.failures >= self.threshold:
            self.state = "open"
            self.opened_at = now
            return True
        if self.state == "open":
            self.opened_at = now
        return False

    @property
    def is_open(self) -> bool:
        return self.state != "closed"


class RetryBudget:
    """Token-bucket retry budget: each ADMITTED request earns
    ``ratio`` tokens (bucket capped at ``cap``); each retry spends one.
    During an outage the bucket drains and retries stop — the router
    surfaces the last error instead of hammering dying replicas."""

    def __init__(self, ratio: Optional[float] = None, cap: float = 10.0):
        self.ratio = (flags.get("RTPU_SERVE_RETRY_BUDGET")
                      if ratio is None else float(ratio))
        self.cap = float(cap)
        self.tokens = self.cap  # start full: cold-start retries allowed
        self._lock = threading.Lock()

    def on_admitted(self) -> None:
        with self._lock:
            self.tokens = min(self.cap, self.tokens + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                return True
            return False


class BreakerBoard:
    """All replica breakers of one deployment + the open-count gauge and
    SERVE_BREAKER_OPEN/CLOSED events."""

    def __init__(self, deployment: str):
        self.deployment = deployment
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()

    def _get(self, replica_id: str) -> CircuitBreaker:
        b = self._breakers.get(replica_id)
        if b is None:
            b = self._breakers[replica_id] = CircuitBreaker(
                flags.get("RTPU_SERVE_BREAKER_THRESHOLD"),
                flags.get("RTPU_SERVE_BREAKER_COOLDOWN_S"))
        return b

    def would_allow(self, replica_id: str) -> bool:
        """Non-mutating pick-time filter: closed, or open with its
        cooldown elapsed and no probe already in flight."""
        with self._lock:
            b = self._breakers.get(replica_id)
            if b is None or b.state == "closed":
                return True
            return (not b._probe_inflight
                    and time.time() - b.opened_at >= b.cooldown_s)

    def admit(self, replica_id: str) -> bool:
        """Mutating admission: an open breaker past cooldown transitions
        to half-open and claims THIS request as its single probe."""
        with self._lock:
            return self._get(replica_id).allow()

    def on_success(self, replica_id: str) -> None:
        with self._lock:
            closed = self._get(replica_id).on_success()
            open_count = self._open_count_locked()
        if closed:
            self._emit("SERVE_BREAKER_CLOSED",
                       f"replica {replica_id[:8]} of {self.deployment} "
                       f"recovered: breaker closed", replica_id)
        self._set_gauge(open_count)

    def on_failure(self, replica_id: str) -> None:
        with self._lock:
            tripped = self._get(replica_id).on_failure()
            open_count = self._open_count_locked()
        if tripped:
            self._emit("SERVE_BREAKER_OPEN",
                       f"replica {replica_id[:8]} of {self.deployment} "
                       f"tripped its circuit breaker "
                       f"({flags.get('RTPU_SERVE_BREAKER_THRESHOLD')} "
                       f"consecutive failures): routing around it",
                       replica_id)
        self._set_gauge(open_count)

    def prune(self, live_ids) -> None:
        """Drop breakers of replicas that left the deployment."""
        live = set(live_ids)
        with self._lock:
            for rid in [r for r in self._breakers if r not in live]:
                self._breakers.pop(rid, None)
            self._set_gauge(self._open_count_locked())

    def _open_count_locked(self) -> int:
        return sum(1 for b in self._breakers.values() if b.is_open)

    def _set_gauge(self, open_count: int) -> None:
        try:
            serve_metrics()["breaker"].set(
                open_count, tags={"deployment": self.deployment})
        except Exception:
            pass

    def _emit(self, kind: str, message: str, replica_id: str) -> None:
        try:
            from ray_tpu.core import events

            events.emit("WARNING" if kind == "SERVE_BREAKER_OPEN" else "INFO",
                        kind, message, source="serve",
                        actor_id=replica_id)
        except Exception:
            pass


def shed(deployment: str, reason: str) -> None:
    """Record one shed on the metrics plane."""
    try:
        serve_metrics()["shed"].inc(
            tags={"deployment": deployment, "reason": reason})
    except Exception:
        pass


def deadline_exceeded(deployment: str) -> None:
    try:
        serve_metrics()["deadline"].inc(tags={"deployment": deployment})
    except Exception:
        pass


def cancelled(deployment: str) -> None:
    try:
        serve_metrics()["cancelled"].inc(tags={"deployment": deployment})
    except Exception:
        pass
