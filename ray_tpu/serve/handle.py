"""DeploymentHandle + Router.

Parity: reference serve/handle.py:711 (DeploymentHandle, .remote :783) →
serve/_private/router.py:312 (Router.assign_request) →
replica_scheduler/pow_2_scheduler.py:49 (PowerOfTwoChoicesReplicaScheduler).
The router keeps a local in-flight counter per replica and picks the less
loaded of two random candidates — queue-length probing without an extra
RPC per request. Replica lists are cached and refreshed from the
controller only when the deployment version bumps or a call fails
(reference LongPollClient config push).
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu import flags
from ray_tpu.core.controller import (ActorDiedError, DeadlineExceededError,
                                     GetTimeoutError, TaskCancelledError,
                                     TaskError, WorkerCrashedError)

from . import admission
from . import context as serve_context
from . import trace
from .controller import CONTROLLER_NAME


class DeploymentNotFoundError(Exception):
    """The handle's deployment no longer exists on the controller."""


def _unwrap(err: BaseException) -> BaseException:
    """Typed control-flow errors (deadline, cancel) travel wrapped in
    TaskError when they fire inside the worker; callers want the type."""
    if isinstance(err, TaskError) and isinstance(
            err.cause, (DeadlineExceededError, TaskCancelledError)):
        return err.cause
    return err


class DeploymentResponse:
    """Future-like result of handle.remote() (reference DeploymentResponse:
    resolves to the result; .result() blocks; ._to_object_ref for chaining)."""

    def __init__(self, ref, router, replica_key, deadline_ts=None,
                 root=None):
        self._ref = ref
        self._router = router
        self._replica_key = replica_key
        self._deadline_ts = deadline_ts
        self._done = False
        # Trace root when THIS call created the trace (bare driver-side
        # handle call): the terminal outcome here becomes the ledger record.
        self._root = root

    def result(self, timeout: Optional[float] = None) -> Any:
        if timeout is None and self._deadline_ts is not None:
            # Default the wait to the request's remaining budget.
            timeout = max(0.0, self._deadline_ts - time.time())
        try:
            out = ray_tpu.get(self._ref, timeout=timeout)
        except GetTimeoutError as e:
            if (self._deadline_ts is not None
                    and time.time() >= self._deadline_ts):
                # The request's own budget ran out — that is the client's
                # deadline, not a replica fault: no breaker strike.
                admission.deadline_exceeded(self._router.name)
                if self._root is not None:
                    self._root.finish("deadline", error=str(e))
                raise DeadlineExceededError(
                    f"request to {self._router.name} deadline exceeded "
                    f"while awaiting the result") from e
            self._router._note_result(self._replica_key, e)
            if self._root is not None:
                self._root.finish("error", error=str(e))
            raise
        except Exception as e:
            e2 = _unwrap(e)
            self._router._note_result(self._replica_key, e2)
            if self._root is not None:
                status = ("deadline"
                          if isinstance(e2, DeadlineExceededError) else
                          "cancelled"
                          if isinstance(e2, TaskCancelledError) else "error")
                self._root.finish(status, error=str(e2))
            if e2 is not e:
                raise e2 from e
            raise
        else:
            self._router._note_result(self._replica_key, None)
            if self._root is not None:
                self._root.finish("ok")
            return out
        finally:
            self._release()

    def cancel(self) -> None:
        """Cancel the in-flight replica call: a queued mailbox entry is
        refused at dequeue, a running one gets the async-raise."""
        try:
            ray_tpu.cancel(self._ref)
        except Exception:
            pass
        admission.cancelled(self._router.name)
        if self._root is not None:
            self._root.finish("cancelled")
        self._release()

    def _release(self) -> None:
        if not self._done:
            self._done = True
            self._router._on_done(self._replica_key)
            if self._root is not None:
                # Fire-and-forget callers never observe the outcome; close
                # the ledger record as ok at release (first finish wins, so
                # an explicit terminal status above is never overwritten).
                self._root.finish("ok")

    def __del__(self):
        # Fire-and-forget callers never call result(); without this the
        # router's in-flight counter for the replica leaks permanently and
        # power-of-two routing starves it of traffic.
        try:
            self._release()
        except Exception:
            pass

    def _to_object_ref(self):
        return self._ref


class DeploymentStreamingResponse:
    """Iterator over a streaming deployment call's items (reference:
    DeploymentResponseGenerator, serve/handle.py). Yields VALUES; the
    underlying transport is the core streaming-generator protocol."""

    def __init__(self, ref_gen, router, replica_key, deadline_ts=None,
                 root=None):
        self._gen = ref_gen
        self._router = router
        self._replica_key = replica_key
        self._deadline_ts = deadline_ts
        self._done = False
        self._exhausted = False
        self._root = root
        self._items = 0

    def __iter__(self):
        return self

    def __next__(self):
        if (self._deadline_ts is not None
                and time.time() > self._deadline_ts):
            # The consumer's budget ran out mid-stream: stop pulling and
            # close the producer (frees its engine slot).
            admission.deadline_exceeded(self._router.name)
            if self._root is not None:
                self._root.finish("deadline", items=self._items)
            self._release()
            raise DeadlineExceededError(
                f"stream from {self._router.name} deadline exceeded")
        try:
            ref = next(self._gen)
        except StopIteration:
            self._exhausted = True
            self._router._note_result(self._replica_key, None)
            if self._root is not None:
                self._root.finish("ok", items=self._items)
            self._release()
            raise
        except Exception as e:
            e2 = _unwrap(e)
            self._router._note_result(self._replica_key, e2)
            if self._root is not None:
                self._root.finish(
                    "deadline" if isinstance(e2, DeadlineExceededError)
                    else "error", error=str(e2), items=self._items)
            self._release()
            raise
        self._items += 1
        return ray_tpu.get(ref)

    def close(self) -> None:
        """Client walked away (HTTP disconnect / explicit abort): close the
        producer generator — the replica sees GeneratorExit and aborts its
        engine request, freeing the KV slot immediately."""
        if not self._done and not self._exhausted:
            admission.cancelled(self._router.name)
            if self._root is not None:
                self._root.finish("cancelled", items=self._items)
        elif self._root is not None:
            self._root.finish("ok", items=self._items)
        self._release()

    def _release(self) -> None:
        if not self._done:
            self._done = True
            self._router._on_done(self._replica_key)
            if self._root is not None:
                # Abandoned without an explicit outcome (__del__): a
                # pre-exhaustion drop is a cancellation. First finish wins.
                self._root.finish(
                    "ok" if self._exhausted else "cancelled",
                    items=self._items)
            close = getattr(self._gen, "close", None)
            if close is not None:
                # Frees a producer stalled in the backpressure window when
                # the consumer walks away mid-stream (HTTP client hangup).
                close()

    def __del__(self):
        try:
            self._release()
        except Exception:
            pass


import weakref

_routers: "weakref.WeakSet" = weakref.WeakSet()
_subscribed_tokens: set = set()


def _ensure_push_subscription() -> None:
    """Subscribe this process once to serve's long-poll push channel
    (reference LongPollClient): replica-set changes invalidate router
    caches immediately instead of waiting out the poll period."""
    from ray_tpu.core import context as ctx

    try:
        wc = ctx.get_worker_context()
    except Exception:
        return
    token = wc.client.token
    if token in _subscribed_tokens:
        return
    _subscribed_tokens.add(token)

    def on_update(data) -> None:
        name = (data or {}).get("name")
        for r in list(_routers):
            if r.name == name:
                r._last_refresh = 0.0  # next assign() refreshes

    try:
        ctx.on_pubsub("serve_updates", on_update)
        wc.client.request({"kind": "subscribe", "channel": "serve_updates"})
    except Exception:
        _subscribed_tokens.discard(token)


class Router:
    REFRESH_PERIOD_S = 3.0

    def __init__(self, deployment_name: str):
        self.name = deployment_name
        self._lock = threading.Lock()
        self._version = -1
        self._replicas: List[Any] = []
        self._inflight: Dict[str, int] = {}
        # Replicas hosted on draining/drained nodes: excluded from picks so
        # requests stop landing on a node that is about to vanish
        # (refreshed with the replica list).
        self._avoid: set = set()
        self._controller = None
        self._last_refresh = 0.0
        # Admission control (RTPU_SERVE_ADMISSION): per-replica circuit
        # breakers, the retry token bucket, and the deployment's queue
        # bound (refreshed with the replica list; None until fetched).
        self._board = admission.BreakerBoard(deployment_name)
        self._budget = admission.RetryBudget()
        self._max_ongoing = 16
        self._max_queued: Optional[int] = None
        # Hot-prefix routing table from the controller's PrefixIndex:
        # prefix hash -> replica ids already holding that prefix's K/V.
        self._prefix_routes: Dict[str, List[str]] = {}
        _routers.add(self)
        _ensure_push_subscription()

    def _ctrl(self):
        if self._controller is None:
            self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller

    def _refresh(self, force: bool = False) -> None:
        now = time.time()
        with self._lock:
            fresh = (self._replicas
                     and now - self._last_refresh < self.REFRESH_PERIOD_S)
            if fresh and not force:
                return
        try:
            version, replicas = ray_tpu.get(
                self._ctrl().get_replicas.remote(self.name))
        except Exception as e:
            if "no deployment" in str(e):
                with self._lock:
                    self._replicas = []
                raise DeploymentNotFoundError(self.name) from e
            raise
        rcfg = None
        if (flags.get("RTPU_SERVE_ADMISSION")
                or flags.get("RTPU_PREFIX_CACHE")):
            try:
                rcfg = ray_tpu.get(
                    self._ctrl().get_routing_config.remote(self.name))
            except Exception:
                rcfg = None  # older controller: keep previous bounds
        avoid = self._replicas_on_draining_nodes(replicas)
        with self._lock:
            self._version = version
            self._replicas = replicas
            self._avoid = avoid
            self._inflight = {r._actor_id: self._inflight.get(r._actor_id, 0)
                              for r in replicas}
            self._last_refresh = now
            if rcfg is not None:
                self._max_ongoing = int(rcfg.get("max_ongoing_requests", 16))
                mq = rcfg.get("max_queued_requests")
                self._max_queued = (flags.get("RTPU_SERVE_MAX_QUEUED")
                                    if mq is None else int(mq))
                self._prefix_routes = rcfg.get("prefix_routes", {})
        self._board.prune([r._actor_id for r in replicas])

    @staticmethod
    def _replicas_on_draining_nodes(replicas) -> set:
        """Actor ids of replicas hosted on draining/drained nodes — the
        scheduler already re-creates them elsewhere; routing there just
        buys a request an ActorDiedError when the node goes."""
        if not replicas:
            return set()
        from ray_tpu.core import context as ctx

        try:
            client = ctx.get_worker_context().client
            nodes = client.request({"kind": "cluster_state"})["nodes"]
            bad = {n["node_id"] for n in nodes
                   if n.get("state", "alive") != "alive"}
            if not bad:
                return set()
            actors = client.request(
                {"kind": "list_state", "what": "actors", "limit": 10000})
            want = {r._actor_id for r in replicas}
            return {a["actor_id"] for a in actors
                    if a["actor_id"] in want and a.get("node_id") in bad}
        except Exception:
            return set()

    def _pick(self, use_breaker: bool = False):
        """Power-of-two-choices over local in-flight counts; replicas on
        draining nodes are out of the draw while any alternative exists,
        and (admission on) so are replicas with open circuit breakers."""
        with self._lock:
            reps = [r for r in self._replicas
                    if r._actor_id not in self._avoid] or self._replicas
            if not reps:
                raise RuntimeError(f"no replicas for {self.name}")
            if use_breaker:
                ok = [r for r in reps
                      if self._board.would_allow(r._actor_id)]
                if not ok:
                    admission.shed(self.name, "breaker_open")
                    raise admission.BackPressureError(
                        f"all replicas of {self.name} have open circuit "
                        f"breakers",
                        retry_after_s=flags.get(
                            "RTPU_SERVE_BREAKER_COOLDOWN_S"))
                reps = ok
            if len(reps) == 1:
                r = reps[0]
            else:
                a, b = random.sample(reps, 2)
                r = a if (self._inflight.get(a._actor_id, 0)
                          <= self._inflight.get(b._actor_id, 0)) else b
            self._inflight[r._actor_id] = self._inflight.get(
                r._actor_id, 0) + 1
            return r

    def _on_done(self, key: str) -> None:
        with self._lock:
            if key in self._inflight and self._inflight[key] > 0:
                self._inflight[key] -= 1

    def _pick_affine(self, model_id: str, exclude: Optional[set] = None,
                     use_breaker: bool = False):
        """Model-affine pick: rendezvous hash over replicas, so one model's
        requests land where it is already loaded (reference model-multiplex
        routing). `exclude` holds replicas that already failed this call —
        the deterministic hash would otherwise retry the same dead one.
        Draining-node replicas leave the hash ring the same way (unless
        nothing else remains)."""
        import hashlib

        with self._lock:
            reps = [r for r in self._replicas
                    if not exclude or r._actor_id not in exclude]
            live = [r for r in reps if r._actor_id not in self._avoid]
            reps = live or reps
            if use_breaker and reps:
                # Breaker-open replicas leave the hash ring too (affinity
                # is a preference; a tripped replica is not).
                ok = [r for r in reps
                      if self._board.would_allow(r._actor_id)]
                if not ok:
                    admission.shed(self.name, "breaker_open")
                    raise admission.BackPressureError(
                        f"all replicas of {self.name} have open circuit "
                        f"breakers",
                        retry_after_s=flags.get(
                            "RTPU_SERVE_BREAKER_COOLDOWN_S"))
                reps = ok
            if not reps:
                raise RuntimeError(f"no replicas for {self.name}")
            # Prefix steering: when the controller's cluster index says
            # some live replicas already HOLD this prefix's K/V, restrict
            # the hash ring to them — the request hits their cache and
            # skips prefill. Falls back to plain rendezvous otherwise.
            holders = self._prefix_routes.get(model_id)
            if holders:
                held = [r for r in reps if r._actor_id in holders]
                if held:
                    reps = held
            r = max(
                reps,
                key=lambda rep: hashlib.md5(
                    f"{model_id}|{rep._actor_id}".encode()).digest(),
            )
            self._inflight[r._actor_id] = self._inflight.get(r._actor_id, 0) + 1
            return r

    def _note_result(self, key: str, err: Optional[BaseException]) -> None:
        """Result-side accounting: successes close breakers, replica
        faults strike them; deadline/cancel outcomes go to their counters
        (client decisions, never a replica's fault)."""
        if err is None:
            if flags.get("RTPU_SERVE_ADMISSION"):
                self._board.on_success(key)
            return
        if isinstance(err, DeadlineExceededError):
            admission.deadline_exceeded(self.name)
            return
        if isinstance(err, TaskCancelledError):
            admission.cancelled(self.name)
            return
        if (flags.get("RTPU_SERVE_ADMISSION")
                and isinstance(err, (ActorDiedError, WorkerCrashedError,
                                     TaskError, GetTimeoutError))):
            self._board.on_failure(key)

    def _admit(self) -> None:
        """Bounded-queue admission: total locally-tracked in-flight beyond
        num_replicas*max_ongoing + max_queued sheds with BackPressureError
        (reference: Serve max_queued_requests, handle-side)."""
        with self._lock:
            n = len(self._replicas)
            total = sum(self._inflight.values())
            max_q = self._max_queued
            if max_q is None:
                max_q = flags.get("RTPU_SERVE_MAX_QUEUED")
        if n == 0 or max_q < 0:
            # Cold start (no replicas yet — the pick path retries) or
            # explicitly unbounded.
            self._budget.on_admitted()
            return
        cap = n * self._max_ongoing + max_q
        if total >= cap:
            admission.shed(self.name, "queue_full")
            raise admission.BackPressureError(
                f"deployment {self.name} is at capacity: {total} requests "
                f"in flight >= {n} replicas x {self._max_ongoing} ongoing "
                f"+ {max_q} queued", retry_after_s=1.0)
        self._budget.on_admitted()

    def assign(self, method_name: str, args, kwargs,
               retries: int = 3, stream: bool = False,
               multiplexed_model_id: str = "",
               deadline_ts: Optional[float] = None,
               request_id: str = "",
               trace_ctx: Optional[dict] = None):
        """Route one request. ``trace_ctx`` is the explicit wire trace
        context from an ingress (HTTP/gRPC proxy); without one, a nested
        call inherits the enclosing request's trace from the serve
        context, and a bare driver-side call ROOTS a new trace here (its
        response wrapper then owns the ledger record)."""
        root = hop = None
        if trace.enabled():
            wire = trace_ctx or trace.current_trace_ctx()
            if wire is None:
                root = trace.start_request(
                    request_id=request_id, deployment=self.name,
                    proto="python", method=method_name)
                wire = root.trace_ctx
            hop = trace.start_hop("serve.assign", kind="router",
                                  trace_ctx=wire, deployment=self.name)
            # Downstream spans parent under the CALLER (root / enclosing
            # replica), not the assign hop: assign ends at dispatch, so
            # execution dwell nested under it would double-count when the
            # waterfall attributes exclusive time.
            trace_ctx = wire
        else:
            trace_ctx = None
        try:
            resp = self._assign(method_name, args, kwargs, retries, stream,
                                multiplexed_model_id, deadline_ts,
                                trace_ctx, hop)
        except BaseException as e:
            if hop is not None:
                hop.end(error=type(e).__name__)
            if root is not None:
                status = ("shed"
                          if isinstance(e, admission.BackPressureError) else
                          "deadline"
                          if isinstance(e, DeadlineExceededError) else
                          "error")
                root.finish(status, error=str(e))
            raise
        if hop is not None:
            hop.end()
        if root is not None:
            resp._root = root
        return resp

    def _assign(self, method_name: str, args, kwargs,
                retries: int = 3, stream: bool = False,
                multiplexed_model_id: str = "",
                deadline_ts: Optional[float] = None,
                trace_ctx: Optional[dict] = None,
                hop=None):
        if deadline_ts is None:
            # Nested composition: a handle call made INSIDE a serve
            # request inherits the enclosing request's budget.
            deadline_ts = serve_context.get_request_deadline()
        # Arrival stamp: set once at the outermost hop, inherited by nested
        # calls — TTFT downstream measures from HERE, queue wait included.
        # The wait itself is forwarded as a per-host monotonic DELTA
        # (upstream accumulation + local dwell), never as an epoch
        # difference across machines, so wall-clock skew can't bias it.
        start_ts = serve_context.get_request_start()
        assign_mono = time.monotonic()
        if start_ts is None:
            start_ts = time.time()
        if deadline_ts is not None and time.time() > deadline_ts:
            admission.deadline_exceeded(self.name)
            raise DeadlineExceededError(
                f"request to {self.name} expired before assignment")
        self._refresh()
        admit = bool(flags.get("RTPU_SERVE_ADMISSION"))
        if admit:
            self._admit()
        last_err: Optional[Exception] = None
        failed: set = set()
        for attempt in range(retries):
            if attempt > 0:
                if admit and not self._budget.try_spend():
                    # Retry budget exhausted: surfacing the error beats
                    # amplifying an outage with retry traffic.
                    break
                # Jittered exponential backoff, never past the deadline.
                delay = min(0.1 * (2 ** (attempt - 1)), 2.0)
                delay *= 0.5 + random.random()
                if deadline_ts is not None:
                    delay = min(delay, max(0.0, deadline_ts - time.time()))
                time.sleep(delay)
                self._refresh(force=True)
                if deadline_ts is not None and time.time() > deadline_ts:
                    admission.deadline_exceeded(self.name)
                    raise DeadlineExceededError(
                        f"request to {self.name} expired while retrying")
            try:
                if multiplexed_model_id:
                    replica = self._pick_affine(multiplexed_model_id, failed,
                                                use_breaker=admit)
                else:
                    replica = self._pick(use_breaker=admit)
            except RuntimeError as e:
                last_err = e
                continue
            rid = replica._actor_id
            if admit and not self._board.admit(rid):
                # Lost the half-open probe race: count as a failed attempt.
                self._on_done(rid)
                last_err = RuntimeError(f"replica {rid[:8]} breaker open")
                continue
            remaining = (None if deadline_ts is None
                         else max(0.0, deadline_ts - time.time()))
            # Queue wait accumulated so far, measured at dispatch time on
            # THIS host's monotonic clock: the enclosing request's elapsed
            # when nested, or the local assign dwell at the outermost hop.
            queue_wait = serve_context.elapsed_s()
            if queue_wait is None:
                queue_wait = time.monotonic() - assign_mono
            if hop is not None:
                hop.attributes.update(attempts=attempt + 1,
                                      replica=rid[:12],
                                      queue_wait_s=round(queue_wait, 6))
            try:
                if stream:
                    ref_gen = replica.handle_request_streaming.options(
                        num_returns="streaming", deadline_s=remaining,
                    ).remote(method_name, args, kwargs,
                             multiplexed_model_id, deadline_ts, start_ts,
                             queue_wait, trace_ctx)
                    return DeploymentStreamingResponse(
                        ref_gen, self, rid, deadline_ts)
                ref = replica.handle_request.options(
                    deadline_s=remaining,
                ).remote(method_name, args, kwargs, multiplexed_model_id,
                         deadline_ts, start_ts, queue_wait, trace_ctx)
                return DeploymentResponse(ref, self, rid, deadline_ts)
            except Exception as e:  # dead replica: drop + refresh
                last_err = e
                failed.add(rid)
                self._on_done(rid)
                if admit:
                    self._board.on_failure(rid)
                self._refresh(force=True)
        raise RuntimeError(
            f"could not assign request to {self.name}: {last_err}")


class DeploymentHandle:
    def __init__(self, deployment_name: str, method_name: str = "__call__",
                 stream: bool = False, multiplexed_model_id: str = "",
                 deadline_s: Optional[float] = None,
                 request_id: str = "",
                 trace_ctx: Optional[dict] = None):
        self.deployment_name = deployment_name
        self._method_name = method_name
        self._stream = stream
        self._multiplexed_model_id = multiplexed_model_id
        self._deadline_s = deadline_s
        self._request_id = request_id
        self._trace_ctx = trace_ctx
        self._router: Optional[Router] = None

    # Routers hold runtime state; rebuild lazily after pickling (handles are
    # injected into replica constructors for composition).
    def __getstate__(self):
        return {"deployment_name": self.deployment_name,
                "_method_name": self._method_name,
                "_stream": self._stream,
                "_multiplexed_model_id": self._multiplexed_model_id,
                "_deadline_s": self._deadline_s}

    def __setstate__(self, state):
        self.deployment_name = state["deployment_name"]
        self._method_name = state["_method_name"]
        self._stream = state.get("_stream", False)
        self._multiplexed_model_id = state.get("_multiplexed_model_id", "")
        self._deadline_s = state.get("_deadline_s")
        self._request_id = ""
        self._trace_ctx = None
        self._router = None

    def options(self, *, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None,
                deadline_s: Optional[float] = None,
                request_id: Optional[str] = None,
                trace_ctx: Optional[dict] = None) -> "DeploymentHandle":
        """``request_id`` names the trace this call roots (an ingress's
        stamped id); ``trace_ctx`` hands over an already-rooted trace
        (the proxies' own root span), making the proxy — not the response
        wrapper — the owner of the ledger record."""
        h = DeploymentHandle(
            self.deployment_name,
            method_name if method_name is not None else self._method_name,
            stream if stream is not None else self._stream,
            (multiplexed_model_id if multiplexed_model_id is not None
             else self._multiplexed_model_id),
            deadline_s if deadline_s is not None else self._deadline_s,
            request_id if request_id is not None else self._request_id,
            trace_ctx if trace_ctx is not None else self._trace_ctx,
        )
        h._router = self._ensure_router()
        return h

    @property
    def method(self):
        return self._method_name

    def _ensure_router(self) -> Router:
        if self._router is None:
            self._router = Router(self.deployment_name)
        return self._router

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        # Cache method-handles and share THIS handle's router: a fresh
        # router per attribute access would cold-RPC the controller on every
        # call and lose the in-flight counts pow-2 routing depends on.
        cache = self.__dict__.setdefault("_method_cache", {})
        h = cache.get(name)
        if h is None:
            h = DeploymentHandle(self.deployment_name, name)
            h._router = self._ensure_router()
            cache[name] = h
        return h

    def remote(self, *args, **kwargs):
        deadline_ts = (None if self._deadline_s is None
                       else time.time() + self._deadline_s)
        return self._ensure_router().assign(
            self._method_name, args, kwargs, stream=self._stream,
            multiplexed_model_id=self._multiplexed_model_id,
            deadline_ts=deadline_ts, request_id=self._request_id,
            trace_ctx=self._trace_ctx)
