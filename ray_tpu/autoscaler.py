"""Autoscaler: grow/shrink the cluster to match pending resource demand.

Parity: reference python/ray/autoscaler/_private/autoscaler.py
(StandardAutoscaler.update :172 — demand from load metrics, launch via a
NodeProvider, idle-node termination) collapsed to the parts that matter for
TPU pods: a provider interface, a demand-driven sizing loop, and idle
timeout scale-down. `LocalNodeProvider` launches host agents on this
machine (the testable provider; cloud/k8s providers implement the same
three methods against their APIs — the reference ships those as pluggable
NodeProvider subclasses too).
"""
from __future__ import annotations

from ray_tpu import flags

import json
import os
import subprocess
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.core import context as ctx


class NodeProvider:
    """Minimal provider surface (reference: autoscaler/node_provider.py)."""

    def create_node(self, resources: Dict[str, float]) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Launch worker nodes as host-agent subprocesses on this machine."""

    def __init__(self, address: str, worker_resources: Optional[Dict[str, float]] = None):
        self.address = address
        self.worker_resources = dict(worker_resources or {"CPU": 1.0})
        self._procs: Dict[str, subprocess.Popen] = {}

    def create_node(self, resources: Optional[Dict[str, float]] = None,
                    tag: Optional[str] = None) -> str:
        """``tag`` overrides the autoscaled label — slice bootstrappers pass
        the pod name so every slice host maps back to its provider node."""
        res = dict(resources or self.worker_resources)
        tag = tag or f"auto-{uuid.uuid4().hex[:8]}"
        env = flags.child_env()
        env.pop("RTPU_ARENA", None)
        env.pop("RTPU_HOST_ID", None)
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.host_agent",
             "--controller", self.address,
             "--resources", json.dumps(res),
             "--labels", json.dumps({"autoscaled": tag})],
            env=env,
        )
        self._procs[tag] = proc
        return tag

    def terminate_node(self, tag: str) -> None:
        """Terminate -> kill escalation + reap (mirrors the controller's
        _watch_spawn teardown): a host agent that ignores SIGTERM — or is
        stuck mid-drain on a dead controller — must not outlive the
        scale-down as a leaked subprocess or linger as a zombie."""
        proc = self._procs.pop(tag, None)
        if proc is None:
            return
        if proc.poll() is not None:
            proc.wait()  # reap the zombie
            return
        try:
            proc.terminate()
        except Exception:
            pass

        def _escalate(proc=proc):
            try:
                proc.wait(timeout=5)
                return
            except subprocess.TimeoutExpired:
                pass
            try:
                proc.kill()
            except Exception:
                pass
            try:
                proc.wait(timeout=10)  # SIGKILL is definitive: reap it
            except Exception:
                pass

        threading.Thread(target=_escalate, daemon=True,
                         name="rtpu-node-reap").start()

    def non_terminated_nodes(self) -> List[str]:
        return [t for t, p in self._procs.items() if p.poll() is None]

    def shutdown(self) -> None:
        for t in list(self._procs):
            self.terminate_node(t)


@dataclass
class AutoscalerConfig:
    min_workers: int = 0
    max_workers: int = 4
    idle_timeout_s: float = 30.0
    update_interval_s: float = 1.0
    # Per-launched-node resources (what one provider node satisfies).
    worker_resources: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1.0})
    # Grace window an idle-scale-down drain gives work that raced onto the
    # node (None -> RTPU_DRAIN_DEADLINE_S); terminate_node is forced once
    # drain_timeout_s passes without the node leaving on its own.
    drain_deadline_s: Optional[float] = None
    drain_timeout_s: float = 60.0


class Autoscaler:
    """Demand-driven sizing loop (reference StandardAutoscaler.update)."""

    def __init__(self, provider: NodeProvider, config: Optional[AutoscalerConfig] = None):
        self.provider = provider
        self.config = config or AutoscalerConfig()
        self._idle_since: Dict[str, float] = {}  # label tag -> idle start
        # tag -> drain start time: nodes we asked the controller to drain;
        # terminate_node runs only once they leave (drain-before-terminate).
        self._draining: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------------- loop

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="rtpu-autoscaler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.update()
            except Exception:
                pass
            self._stop.wait(self.config.update_interval_s)

    # --------------------------------------------------------------- update

    def _state(self) -> Dict[str, Any]:
        return ctx.get_worker_context().client.request(
            {"kind": "autoscaler_state"})

    def update(self) -> None:
        """One reconcile pass: launch for unsatisfied demand, reap idle."""
        cfg = self.config
        state = self._state()
        managed = set(self.provider.non_terminated_nodes())
        live_tags = {
            n["labels"].get("autoscaled"): n
            for n in state["nodes"]
            if n["alive"] and n["labels"].get("autoscaled")
        }

        # Scale up: unsatisfied demand -> nodes to add (each provider node
        # contributes worker_resources).
        demands = state["demands"]
        deficit_nodes = 0
        if demands:
            # Demand not placeable on current availability, bin-packed
            # against what one new node offers.
            # Draining nodes take no placements: their capacity must not
            # mask a deficit (or the drained node's work never re-lands).
            free: List[Dict[str, float]] = [
                dict(n["available"]) for n in state["nodes"]
                if n["alive"] and n.get("state", "alive") == "alive"]
            unsat = []
            for d in demands:
                placed = False
                for f in free:
                    if all(f.get(k, 0.0) >= v for k, v in d.items()):
                        for k, v in d.items():
                            f[k] -= v
                        placed = True
                        break
                if not placed:
                    unsat.append(d)
            cap = dict(cfg.worker_resources)
            node_free: Dict[str, float] = {}
            for d in unsat:
                if all(node_free.get(k, 0.0) >= v for k, v in d.items()):
                    for k, v in d.items():
                        node_free[k] -= v
                    continue
                if all(cap.get(k, 0.0) >= v for k, v in d.items()):
                    deficit_nodes += 1
                    node_free = dict(cap)
                    for k, v in d.items():
                        node_free[k] -= v
                # Demands a single node can never satisfy are skipped (the
                # reference logs these as infeasible).
        # Launched-but-unregistered nodes already count against the demand:
        # without this, every pass re-launches for the same deficit while
        # the first node is still booting (reference: pending-launch
        # accounting in StandardAutoscaler).
        pending = len(managed) - sum(1 for t in managed if t in live_tags)
        target_new = min(
            max(0, deficit_nodes - pending),
            max(0, cfg.max_workers - len(managed)),
        )
        for _ in range(target_new):
            self.provider.create_node(dict(cfg.worker_resources))
        if target_new:
            from ray_tpu.core import events

            events.emit(
                "INFO", "AUTOSCALER_SCALE_UP",
                f"autoscaler launching {target_new} node(s) for "
                f"{deficit_nodes} unsatisfied demand node(s)",
                source="autoscaler",
                data={"new_nodes": target_new,
                      "resources": dict(cfg.worker_resources)})

        # Scale down: managed nodes idle past the timeout (respect min).
        # Drain-before-terminate (reference: the autoscaler's DrainNode
        # call ahead of node termination, autoscaler.proto:334): the
        # controller stops scheduling there, migrates actors, and lets a
        # task that raced onto the idle-marked node finish or re-queue —
        # only once the node has actually left does the provider reap it.
        now = time.monotonic()
        removable = []
        for tag in managed:
            if tag in self._draining:
                continue
            node = live_tags.get(tag)
            if node is None:
                continue  # still registering
            if node["busy"] or demands:
                self._idle_since.pop(tag, None)
                continue
            since = self._idle_since.setdefault(tag, now)
            if now - since >= cfg.idle_timeout_s:
                removable.append((tag, node["node_id"]))
        already = len(self._draining)
        can_remove = max(0, len(managed) - already - cfg.min_workers)
        for tag, node_id in removable[:can_remove]:
            try:
                ctx.get_worker_context().client.request(
                    {"kind": "drain_node", "node_id": node_id,
                     "reason": "idle_scale_down",
                     "deadline_s": cfg.drain_deadline_s})
            except Exception:
                continue  # retry the drain next pass
            from ray_tpu.core import events

            events.emit(
                "INFO", "AUTOSCALER_SCALE_DOWN",
                f"autoscaler draining idle node {node_id[:8]} "
                f"(idle > {cfg.idle_timeout_s:.0f}s)",
                source="autoscaler", node_id=node_id,
                data={"idle_timeout_s": cfg.idle_timeout_s})
            self._draining[tag] = now
            self._idle_since.pop(tag, None)
        # Reap drained nodes: the controller's drain completion shuts the
        # agent down, so the provider call is normally just a zombie reap;
        # a drain stuck past drain_timeout_s is forced out.
        for tag, t0 in list(self._draining.items()):
            node = live_tags.get(tag)
            departed = node is None or node.get("state") in ("drained",
                                                            "dead")
            if departed or now - t0 >= cfg.drain_timeout_s:
                self.provider.terminate_node(tag)
                self._draining.pop(tag, None)


def request_resources(num_cpus: Optional[int] = None,
                      bundles: Optional[List[Dict[str, float]]] = None) -> None:
    """Parity: ray.autoscaler.sdk.request_resources — pin a demand floor.
    Implemented as placeholder pending tasks is unnecessary here: the
    autoscaler reads real queue demand; this records an advisory ask in the
    controller KV for operators/tests to inspect."""
    ask: List[Dict[str, float]] = list(bundles or [])
    if num_cpus:
        ask.append({"CPU": float(num_cpus)})
    ctx.get_worker_context().client.request(
        {"kind": "kv_put", "ns": "__autoscaler__", "key": "request",
         "value": json.dumps(ask).encode()})
