"""Distributed futures core: controller, workers, object store, public API."""
