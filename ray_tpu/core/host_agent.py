"""Per-host daemon: the node-level agent of the cluster.

Role-equivalent to the reference's raylet (ray: src/ray/raylet/main.cc:123
starting NodeManager + local object manager, node_manager.h:119): one agent
per host, it

- registers its host as a node with the controller over TCP,
- owns the host's object arena (creates it; local workers inherit it),
- spawns and supervises worker processes on *its* host when the controller
  grants a lease (spawn delegation replaces the controller's local Popen),
- serves chunked object pulls to remote peers (core.transfer protocol,
  reference object_manager.proto Push/Pull),
- heartbeats node health + arena stats to the controller
  (gcs_health_check_manager.h:39 semantics),
- fate-shares: when the controller connection drops, it kills its workers
  and exits (raylet workers fate-share with their raylet).

Entrypoint: ``python -m ray_tpu.core.host_agent --controller HOST:PORT``.
Tests simulate a second host on one machine by overriding RTPU_HOST_ID
(--host-id), which forces every cross-"host" object read through the real
TCP pull path.
"""
from __future__ import annotations

from ray_tpu import flags

import argparse
import asyncio
import json
import os
import signal
import subprocess
import sys
import time
import uuid
from typing import Any, Dict, Optional

from . import native_store, protocol, transfer
from .ids import NodeID

HEARTBEAT_S = flags.get("RTPU_HEARTBEAT_S")


class HostAgent:
    def __init__(
        self,
        controller_addr: str,
        *,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        host_id: Optional[str] = None,
        serve_host: str = "127.0.0.1",
        serve_port: int = 0,
    ):
        self.controller_addr = controller_addr
        self.node_id = NodeID.generate()
        self.resources = dict(resources or {"CPU": float(os.cpu_count() or 1)})
        # Unit-instance chip pool for TPU_VISIBLE_CHIPS assignment (the agent
        # owns its worker processes, so it owns the per-worker chip ids —
        # reference: raylet-side GPU instance accounting).
        self.tpu_free: list = list(range(int(self.resources.get("TPU", 0))))
        self.tpu_alloc: Dict[str, list] = {}  # spawn_token -> chip ids
        self.labels = dict(labels or {})
        self.serve_host = serve_host
        self.serve_port = serve_port
        self.ctrl: Optional[protocol.Connection] = None
        self.server: Optional[asyncio.base_events.Server] = None
        self.procs: Dict[str, subprocess.Popen] = {}  # spawn_token -> proc
        self.worker_tokens: Dict[str, str] = {}  # worker_id -> spawn_token
        self._stop = asyncio.Event()
        self._draining = False  # a self-drain request is in flight
        # Unshipped cluster events (core/events.py records): flushed on the
        # heartbeat path, so delivery is reconnect-safe for free — a batch
        # pending across a controller bounce rides the first heartbeat on
        # the re-established connection.
        self._pending_events: list = []
        if host_id:
            flags.set_env("RTPU_HOST_ID", host_id)
        from .object_store import current_host_id

        self.host_id = current_host_id()
        # The agent owns this host's arena; spawned workers inherit RTPU_ARENA.
        self.arena = native_store.create_node_arena(self.node_id)

    # ---------------------------------------------------------------- startup

    async def start(self) -> None:
        self.server = await asyncio.start_server(
            self._on_peer, self.serve_host, self.serve_port
        )
        self.serve_port = self.server.sockets[0].getsockname()[1]
        host, port = self.controller_addr.rsplit(":", 1)
        self.ctrl = await protocol.connect(
            host, int(port), self._on_controller_msg, name="agent->controller"
        )
        await self.ctrl.request(self._register_msg())
        loop = asyncio.get_running_loop()
        loop.create_task(self._heartbeat_loop())
        loop.create_task(self._watch_controller())
        loop.create_task(self._reap_loop())
        if flags.get("RTPU_PREEMPTION_WATCHER"):
            loop.create_task(self._preemption_watch_loop())

    def _register_msg(self) -> Dict[str, Any]:
        return {
            "kind": "register_node",
            "node_id": self.node_id,
            "resources": self.resources,
            "labels": self.labels,
            "agent_addr": [self.serve_host, self.serve_port],
            "host_id": self.host_id,
            "arena": self.arena.name if self.arena else None,
            # Live state, re-reported on reconnect so a restarted
            # controller can reconcile (harmless on first contact): chips
            # currently granted to worker processes, and the live workers.
            "tpu_in_use": sorted(
                c for ids in self.tpu_alloc.values() for c in ids),
            "workers": {tok: proc.pid for tok, proc in self.procs.items()
                        if proc.poll() is None},
        }

    async def _watch_controller(self) -> None:
        """Reconnect with capped exponential backoff when the controller
        connection drops (reference: raylet re-registration on
        NotifyGCSRestart, node_manager.proto:373). Only after the reconnect
        deadline passes does the agent fate-share: kill workers and exit."""
        while not self._stop.is_set():
            ctrl = self.ctrl
            await ctrl.closed.wait()
            if self._stop.is_set():
                return
            if self.ctrl is not ctrl:
                continue  # deliberately swapped by _try_reregister
            if not await self._reconnect():
                self._terminate_workers()
                self._stop.set()
                return

    async def _reconnect(self) -> bool:
        host, port = self.controller_addr.rsplit(":", 1)
        max_s = flags.get("RTPU_RECONNECT_MAX_S")
        deadline = time.monotonic() + max_s
        backoff = flags.get("RTPU_RECONNECT_BACKOFF_S")
        while not self._stop.is_set():
            try:
                ctrl = await protocol.connect(
                    host, int(port), self._on_controller_msg,
                    name="agent->controller")
                await ctrl.request(self._register_msg(), timeout=10)
                self.ctrl = ctrl
                sys.stderr.write(
                    f"[host_agent] reconnected to controller at "
                    f"{self.controller_addr}\n")
                return True
            except Exception as e:
                now = time.monotonic()
                if now >= deadline:
                    sys.stderr.write(
                        f"[host_agent] controller unreachable after "
                        f"{max_s:.0f}s ({e!r}); shutting down\n")
                    return False
                await asyncio.sleep(min(backoff, deadline - now))
                backoff = min(backoff * 2, 2.0)
        return False

    async def _try_reregister(self, rpc_t: float) -> bool:
        """Dial a fresh connection and re-register on it WITHOUT dropping
        the current one; only a successful handshake swaps them (the
        controller's register handler updates node.agent_conn, so the old
        conn's close is then harmless)."""
        host, port = self.controller_addr.rsplit(":", 1)
        ctrl = None
        try:
            ctrl = await protocol.connect(
                host, int(port), self._on_controller_msg,
                name="agent->controller")
            await ctrl.request(self._register_msg(),
                               timeout=max(rpc_t * 2, 2.0))
        except Exception:
            if ctrl is not None:
                try:
                    await ctrl.close()
                except Exception:
                    pass
            return False
        old, self.ctrl = self.ctrl, ctrl
        sys.stderr.write("[host_agent] re-registered over a fresh "
                         "connection after unacknowledged heartbeats\n")
        try:
            await old.close()
        except Exception:
            pass
        return True

    # ------------------------------------------------- drain / preemption

    def _emit_event(self, severity: str, kind: str, message: str,
                    **entities) -> None:
        """Queue one cluster event for the next heartbeat flush."""
        from . import events

        if not events.enabled():
            return
        self._pending_events.append(events.make_event(
            severity, "agent", kind, message,
            node_id=entities.pop("node_id", self.node_id), **entities))
        del self._pending_events[:-256]  # bounded, oldest drop first

    async def _flush_events(self) -> None:
        if not self._pending_events:
            return
        batch, self._pending_events = self._pending_events, []
        try:
            await self.ctrl.send({"kind": "cluster_events", "events": batch})
        except Exception:
            # Controller unreachable: re-buffer for the next heartbeat.
            self._pending_events = batch + self._pending_events
            del self._pending_events[:-256]

    async def _preemption_watch_loop(self) -> None:
        """Poll the cloud metadata preemption endpoint (GCE: the
        instance/preempted key flips to TRUE ~30s before the VM dies;
        RTPU_PREEMPTION_URL makes it pluggable so tests serve a fake) and
        self-drain on the first notice — the cluster migrates this host's
        actors/tasks/objects during the notice window instead of taking a
        crash."""
        url = flags.get("RTPU_PREEMPTION_URL")
        poll = flags.get("RTPU_PREEMPTION_POLL_S")
        while not self._stop.is_set():
            try:
                await asyncio.wait_for(self._stop.wait(), poll)
                return
            except asyncio.TimeoutError:
                pass
            try:
                notice = await asyncio.to_thread(self._poll_preemption, url)
            except Exception:
                continue  # metadata server flake: keep watching
            if notice:
                sys.stderr.write(
                    f"[host_agent] preemption notice at {url}; draining "
                    f"node {self.node_id[:8]}\n")
                self._emit_event(
                    "WARNING", "NODE_PREEMPTION_NOTICE",
                    f"preemption notice received on node "
                    f"{self.node_id[:8]}; self-draining",
                    data={"url": url})
                self.initiate_drain("preemption")
                return

    @staticmethod
    def _poll_preemption(url: str) -> bool:
        import urllib.request

        req = urllib.request.Request(
            url, headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=2) as resp:
            body = resp.read(256).decode("utf-8", "replace").strip()
        return body.upper() not in ("", "FALSE", "NONE", "0")

    def initiate_drain(self, reason: str) -> None:
        """Ask the controller to drain this node (idempotent). Called from
        the preemption watcher and the SIGTERM handler — both run on the
        event loop. A second call (second SIGTERM, or drain already
        pending) forces immediate shutdown instead."""
        if self._draining:
            self._stop.set()
            return
        self._draining = True
        deadline_s = flags.get("RTPU_DRAIN_DEADLINE_S")

        async def _drain():
            try:
                await self.ctrl.request(
                    {"kind": "drain_node", "node_id": self.node_id,
                     "reason": reason, "deadline_s": deadline_s},
                    timeout=10)
            except Exception as e:
                sys.stderr.write(
                    f"[host_agent] drain request failed ({e!r}); "
                    f"shutting down hard\n")
                self._stop.set()
                return
            # The controller finishes the drain by sending us "shutdown".
            # Backstop: if that never arrives (controller died mid-drain),
            # exit once the grace window (plus slack) has passed rather
            # than serving a cluster that thinks we're gone.
            try:
                await asyncio.wait_for(self._stop.wait(), deadline_s + 15)
            except asyncio.TimeoutError:
                sys.stderr.write(
                    "[host_agent] drain never completed; exiting\n")
                self._stop.set()

        asyncio.get_running_loop().create_task(_drain())

    async def run_forever(self) -> None:
        await self._stop.wait()
        if self.server is not None:
            self.server.close()
        self._terminate_workers()
        native_store.close_arena(destroy=True)

    def _terminate_workers(self) -> None:
        for proc in self.procs.values():
            if proc.poll() is None:
                try:
                    proc.terminate()
                except Exception:
                    pass
        self.procs.clear()

    # --------------------------------------------------------- controller rpc

    async def _on_controller_msg(self, conn, msg: Dict[str, Any]) -> Any:
        kind = msg["kind"]
        if kind == "spawn_worker":
            renv = msg.get("runtime_env")
            if renv and (renv.get("pip") or renv.get("conda")):
                # venv/conda creation takes seconds: keep the agent loop
                # live (same pip-or-conda gate as the controller's local
                # spawn path — they must not diverge or one side silently
                # launches env-hashed workers without the env).
                from .runtime_env import spawner_python

                try:
                    python = await asyncio.to_thread(spawner_python, renv)
                except Exception as e:
                    sys.stderr.write(
                        f"[host_agent] runtime env build failed: {e!r}\n")
                    await self.ctrl.send(
                        {"kind": "spawn_exited",
                         "spawn_token": msg["spawn_token"],
                         "node_id": self.node_id, "returncode": -1,
                         "env_failed": renv.get("hash", ""),
                         "env_error": str(e)[:500]})
                    return {"ok": False}
                return self._spawn_worker(msg, python=python)
            return self._spawn_worker(msg)
        if kind == "kill_worker":
            tok = msg.get("spawn_token") or self.worker_tokens.get(
                msg.get("worker_id", "")
            )
            # Terminate but leave the proc in self.procs: chips must return
            # to the pool only when the process has ACTUALLY exited (the
            # reap loop frees them) — a SIGTERM'd worker can hold the
            # devices open for seconds, and granting its chips to a new
            # spawn meanwhile hits libtpu "device in use".
            proc = self.procs.get(tok) if tok else None
            if proc is not None and proc.poll() is None:
                try:
                    proc.terminate()
                except Exception:
                    pass
            return {"ok": True}
        if kind == "kill_pgid":
            # Job-plane orphan/stop sweep: escalate through one process
            # group (a dead supervisor's entrypoint and its shell=True
            # children live in their own session on THIS host). Runs off
            # the agent loop — the grace window would stall heartbeats.
            from .job_manager import kill_process_group

            ok = await asyncio.to_thread(
                kill_process_group, int(msg.get("pgid") or 0),
                float(msg.get("grace_s") or 3.0))
            return {"ok": bool(ok)}
        if kind == "free_object":
            loc = msg["loc"]
            from .object_store import free_location

            try:
                free_location(loc)
            except Exception:
                pass
            return {"ok": True}
        if kind == "shutdown":
            self._stop.set()
            return {"ok": True}
        if kind in transfer.PULL_SERVER_KINDS:
            return await transfer.handle_pull_server_message(conn, msg)
        if kind == "replicate_push":
            # Broadcast source on this host: stream the object's bytes down
            # the hop chain (each byte leaves this host once) and report
            # how many were shipped so the controller's per-broadcast
            # source-byte accounting stays truthful.
            async def _push(msg=msg):
                sent = 0
                err = None
                try:
                    sent = await transfer.push_replicate_chain(
                        msg["loc"], msg["chain"], msg["bid"],
                        chunk=msg.get("chunk"), window=msg.get("window"))
                except Exception as e:  # noqa: BLE001 — reported, re-routed
                    err = repr(e)[:300]
                try:
                    await self.ctrl.send(
                        {"kind": "replicate_push_done", "bid": msg["bid"],
                         "bytes": sent, "error": err})
                except Exception:
                    pass

            asyncio.get_running_loop().create_task(_push())
            return {"ok": True}
        if kind == "list_logs":
            # This host's worker log files with sizes (cluster log index
            # building block; reference: the dashboard log API's per-node
            # file listing).
            from .worker_logs import list_log_files

            return list_log_files()
        if kind == "tail_log":
            # Bounded tail of one worker log (dashboard log viewer + crash
            # post-mortems; attribution markers are stripped so the tail
            # reads like the process's console did).
            from .worker_logs import log_dir, read_tail

            name = os.path.basename(msg["name"])  # no traversal
            nbytes = min(int(msg.get("bytes", 65536)), 1 << 20)
            try:
                return read_tail(os.path.join(log_dir(), name), nbytes)
            except OSError as e:
                return f"<log unavailable: {e}>"
        if kind == "get_log":
            # Ranged / task-filtered / long-poll log read (the `rtpu logs`
            # fetch + follow backend; reference: the `ray logs` CLI and
            # dashboard log endpoints streaming any file on any node).
            from .worker_logs import serve_get_log_wait

            m = dict(msg)
            m["name"] = os.path.basename(m.get("name") or "")
            return await serve_get_log_wait(m)
        raise ValueError(f"host_agent: unknown message kind {kind!r}")

    def _spawn_worker(self, msg: Dict[str, Any],
                      python: Optional[str] = None) -> Dict[str, Any]:
        spawn_token = msg["spawn_token"]
        env = flags.child_env()
        if msg.get("runtime_env"):
            env["RTPU_RUNTIME_ENV"] = json.dumps(msg["runtime_env"])
        env["RTPU_CONTROLLER"] = self.controller_addr
        env["RTPU_NODE_ID"] = self.node_id
        env["RTPU_SPAWN_TOKEN"] = spawn_token
        env["RTPU_HOST_ID"] = self.host_id
        if self.arena is not None:
            env["RTPU_ARENA"] = self.arena.name
        if msg.get("tpu"):
            env["RTPU_TPU_WORKER"] = "1"
            # Per-worker chip visibility (reference tpu.py TPU_VISIBLE_CHIPS;
            # controller's local-spawn path does the same). Pool exhausted ->
            # unrestricted visibility; the float resource is the hard limit.
            k = max(1, int(msg.get("tpu_chips") or 1))
            if len(self.tpu_free) >= k:
                ids, self.tpu_free = self.tpu_free[:k], self.tpu_free[k:]
                env["TPU_VISIBLE_CHIPS"] = ",".join(map(str, ids))
                self.tpu_alloc[spawn_token] = ids
            else:  # partial slice would under-provision: spawn unrestricted
                env.pop("TPU_VISIBLE_CHIPS", None)
        else:
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env.pop("TPU_VISIBLE_CHIPS", None)  # never inherit chip grants
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        if msg.get("sys_path"):
            env["RTPU_SYS_PATH"] = msg["sys_path"]
        env.setdefault("JAX_PLATFORMS", "cpu")
        from .worker_logs import worker_log_file

        log_f = worker_log_file(spawn_token)
        cmd = [python or sys.executable, "-m", "ray_tpu.core.worker_main"]
        renv_spec = msg.get("runtime_env")
        if renv_spec and renv_spec.get("container"):
            from .runtime_env import container_command

            cmd = container_command(renv_spec, cmd)
        try:
            proc = subprocess.Popen(
                cmd,
                env=env,
                stdout=log_f,
                stderr=subprocess.STDOUT if log_f else None,
            )
        except OSError as e:
            # Unwind the chip grant: a launch that never produced a process
            # has no reap event to return the chips through. The synthetic
            # spawn_exited unwinds the controller's spawning counters the
            # same way a pre-register death would.
            self.tpu_free.extend(self.tpu_alloc.pop(spawn_token, []))
            sys.stderr.write(f"[host_agent] worker launch failed: {e!r}\n")
            self._emit_event(
                "ERROR", "WORKER_LAUNCH_FAILED",
                f"worker launch failed on node {self.node_id[:8]}: {e!r}",
                data={"error": str(e)})
            asyncio.get_running_loop().create_task(self.ctrl.send(
                {"kind": "spawn_exited", "spawn_token": spawn_token,
                 "node_id": self.node_id, "returncode": -1}))
            return {"ok": False, "error": str(e)}
        self.procs[spawn_token] = proc
        return {"ok": True, "pid": proc.pid}

    async def _reap_loop(self) -> None:
        """Report workers that die before (or after) registering so the
        controller's spawning counters and worker table stay truthful."""
        while not self._stop.is_set():
            await asyncio.sleep(0.2)
            for tok, proc in list(self.procs.items()):
                if proc.poll() is not None:
                    self.procs.pop(tok, None)
                    self.tpu_free.extend(self.tpu_alloc.pop(tok, []))
                    try:
                        await self.ctrl.send(
                            {"kind": "spawn_exited", "spawn_token": tok,
                             "node_id": self.node_id,
                             "returncode": proc.returncode}
                        )
                    except Exception:
                        pass

    def _proc_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-worker-process cpu%/rss (reference: the dashboard agent's
        reporter sampling its node's worker processes). cpu_percent uses
        the interval since the previous heartbeat's call — free."""
        out: Dict[str, Dict[str, float]] = {}
        try:
            import psutil
        except Exception:
            return out
        for token, proc in list(self.procs.items()):
            if proc.poll() is not None:
                continue
            try:
                p = self._psutil_cache.get(proc.pid)
                if p is None:
                    p = psutil.Process(proc.pid)
                    self._psutil_cache[proc.pid] = p
                    p.cpu_percent(None)  # prime the interval
                with p.oneshot():
                    out[str(proc.pid)] = {
                        "cpu_percent": p.cpu_percent(None),
                        "rss": float(p.memory_info().rss),
                    }
            except Exception:
                self._psutil_cache.pop(proc.pid, None)
        return out

    async def _heartbeat_loop(self) -> None:
        self._psutil_cache: Dict[int, Any] = {}
        # Partition detection (RTPU_RPC_TIMEOUT_S > 0): heartbeats become
        # acknowledged requests; once the controller has not answered one
        # for RTPU_NODE_TIMEOUT_S the agent assumes the connection is
        # blackholed-but-open and closes it, entering the reconnect loop —
        # a healed partition re-registers (the controller's suspect phase
        # kept the node's actors), a dead controller fate-shares as before.
        # 0 (default) keeps heartbeats fire-and-forget.
        last_ack = time.monotonic()
        while not self._stop.is_set():
            stats = self.arena.stats() if self.arena else {}
            try:
                import psutil

                mem_fraction = psutil.virtual_memory().percent / 100.0
            except Exception:
                mem_fraction = None
            try:
                import psutil as _ps

                cpu_percent = _ps.cpu_percent(None)
            except Exception:
                cpu_percent = None
            from .worker_logs import log_volume_bytes
            try:
                from .object_store import spill_stats

                spill = spill_stats()
            except Exception:
                spill = {}
            try:
                from .object_store import host_channel_stats

                channels = host_channel_stats()
            except Exception:
                channels = {}

            hb = {
                "kind": "heartbeat",
                "node_id": self.node_id,
                "t": time.time(),
                "arena": stats,
                # Host-wide spill usage ({files, bytes}): the census
                # "spill" tier and the `rtpu status` STORE column.
                "spill": spill,
                # Channel-fabric footprint ({segments, bytes}): live
                # rtpu_ch_* shm rings on this host — the node-level view
                # of the compiled-DAG channel plane.
                "channels": channels,
                "num_workers": len(self.procs),
                "mem_fraction": mem_fraction,
                # Host CPU% (the `rtpu status` per-node column).
                "cpu_percent": cpu_percent,
                "proc_stats": self._proc_stats(),
                # Per-node log volume (rtpu_worker_log_bytes gauge).
                "log_bytes": log_volume_bytes(),
            }
            rpc_t = flags.get("RTPU_RPC_TIMEOUT_S")
            if rpc_t:
                try:
                    await self.ctrl.request(hb, timeout=max(rpc_t, 1.0))
                    last_ack = time.monotonic()
                except Exception:
                    if (time.monotonic() - last_ack
                            > flags.get("RTPU_NODE_TIMEOUT_S")):
                        # Suspected partition: try a PARALLEL re-register.
                        # The old connection stays up meanwhile — closing
                        # it would FIN through the blackhole and make the
                        # controller declare this node dead, exactly the
                        # churn the suspect phase avoids; an app-level heal
                        # resumes the old conn, a TCP-level death heals via
                        # the fresh dial.
                        if await self._try_reregister(rpc_t):
                            last_ack = time.monotonic()
            else:
                try:
                    await self.ctrl.send(hb)
                except Exception:
                    pass
            await self._flush_events()
            try:
                await asyncio.wait_for(self._stop.wait(), HEARTBEAT_S)
            except asyncio.TimeoutError:
                pass

    # ------------------------------------------------------------ pull server

    async def _on_peer(self, reader, writer) -> None:
        conn = protocol.Connection(reader, writer, self._on_peer_msg, name="agent-peer")
        conn.start()
        await conn.closed.wait()

    async def _on_peer_msg(self, conn, msg: Dict[str, Any]) -> Any:
        kind = msg["kind"]
        if kind in transfer.PULL_SERVER_KINDS:
            return await transfer.handle_pull_server_message(conn, msg)
        if kind in transfer.REPLICATE_KINDS:
            # Broadcast chain hop: write incoming chunks into this host's
            # arena/shm and forward downstream while still receiving; the
            # sealed replica is reported to the controller over the agent's
            # control connection (reconnect-safe channel).
            async def _report(payload):
                await self.ctrl.send(payload)

            return await transfer.handle_replicate_message(
                conn, msg, node_id=self.node_id, report=_report)
        if kind == "ping":
            return {"pong": True, "node_id": self.node_id}
        raise ValueError(f"host_agent peer: unknown message kind {kind!r}")


async def _amain(args) -> int:
    agent = HostAgent(
        args.controller,
        resources=json.loads(args.resources) if args.resources else None,
        labels=json.loads(args.labels) if args.labels else None,
        host_id=args.host_id or None,
        serve_port=args.port,
    )

    def _sigterm(*_a):
        # Graceful departure: SIGTERM triggers a drain — workers keep
        # running while the controller migrates actors and re-queues tasks
        # — instead of an immediate worker kill. A second SIGTERM (or
        # SIGINT) forces the old immediate shutdown.
        agent.initiate_drain("manual")

    def _sigint(*_a):
        agent._stop.set()

    loop = asyncio.get_running_loop()
    try:
        loop.add_signal_handler(signal.SIGTERM, _sigterm)
    except NotImplementedError:
        pass
    try:
        loop.add_signal_handler(signal.SIGINT, _sigint)
    except NotImplementedError:
        pass
    try:
        await agent.start()
    except (ConnectionError, OSError) as e:
        sys.stderr.write(f"host_agent: cannot reach controller: {e!r}\n")
        return 2
    await agent.run_forever()
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description="ray_tpu per-host agent daemon")
    ap.add_argument("--controller", required=True, help="controller HOST:PORT")
    ap.add_argument("--resources", default="", help='JSON, e.g. {"CPU": 4}')
    ap.add_argument("--labels", default="", help="JSON labels")
    ap.add_argument("--host-id", default="", help="override host identity (tests)")
    ap.add_argument("--port", type=int, default=0, help="pull-server port")
    args = ap.parse_args()
    if args.host_id:
        # Must be set before the arena env leaks to children.
        flags.set_env("RTPU_HOST_ID", args.host_id)
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    raise SystemExit(main())
