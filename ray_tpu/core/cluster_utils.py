"""In-process virtual multi-node cluster for tests.

Reference: python/ray/cluster_utils.py:135 `Cluster.add_node` — the mechanism
by which "multi-node" behavior is tested on one machine. Here a virtual node
is a resource pool in the controller with its own worker-process pool.
"""
from __future__ import annotations

from typing import Dict, Optional

from . import api, context as ctx


class Cluster:
    """Drive the controller owned by `ray_tpu.init()` to add virtual nodes."""

    def __init__(self, initialize_head: bool = True, head_resources: Optional[Dict[str, float]] = None):
        self.head_handle = None
        if initialize_head:
            res = dict(head_resources or {"CPU": 1})
            num_cpus = int(res.pop("CPU", 1))
            self.head_handle = api.init(num_cpus=num_cpus, resources=res)

    def add_node(self, resources: Dict[str, float], labels: Optional[Dict[str, str]] = None) -> str:
        wc = ctx.get_worker_context()
        return wc.client.request(
            {"kind": "add_node", "resources": dict(resources), "labels": labels or {}}
        )["node_id"]

    def shutdown(self) -> None:
        api.shutdown()
