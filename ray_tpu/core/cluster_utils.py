"""Multi-node cluster harness for tests.

Reference: python/ray/cluster_utils.py:135 `Cluster.add_node` — the mechanism
by which "multi-node" behavior is tested on one machine. Two node flavors:

- virtual (default): a resource pool inside the controller with its own
  worker-process pool — cheap, single-host by construction.
- remote (``remote=True``): a real `ray_tpu.core.host_agent` subprocess with
  its own object arena, pull server, heartbeats, and worker pool. Passing a
  distinct ``host_id`` simulates a second machine: every cross-host object
  read then streams over TCP through the agent (reference:
  src/ray/raylet/main.cc daemon startup + object_manager push/pull).
"""
from __future__ import annotations

from ray_tpu import flags

import json
import subprocess
import sys
import time
from typing import Dict, List, Optional

from . import api, context as ctx


class Cluster:
    """Drive the controller owned by `ray_tpu.init()` to add nodes."""

    def __init__(self, initialize_head: bool = True, head_resources: Optional[Dict[str, float]] = None):
        self.head_handle = None
        self._agent_procs: List[subprocess.Popen] = []
        if initialize_head:
            res = dict(head_resources or {"CPU": 1})
            num_cpus = int(res.pop("CPU", 1))
            self.head_handle = api.init(num_cpus=num_cpus, resources=res)

    def add_node(
        self,
        resources: Dict[str, float],
        labels: Optional[Dict[str, str]] = None,
        *,
        remote: bool = False,
        host_id: Optional[str] = None,
        timeout: float = 20.0,
    ) -> str:
        wc = ctx.get_worker_context()
        if not remote:
            return wc.client.request(
                {"kind": "add_node", "resources": dict(resources), "labels": labels or {}}
            )["node_id"]

        before = {n["node_id"] for n in wc.client.request({"kind": "cluster_state"})["nodes"]}
        cmd = [
            sys.executable, "-m", "ray_tpu.core.host_agent",
            "--controller", wc.extra.get("address", ""),
            "--resources", json.dumps(dict(resources)),
        ]
        if labels:
            cmd += ["--labels", json.dumps(labels)]
        if host_id:
            cmd += ["--host-id", host_id]
        import os

        env = flags.child_env()
        env.pop("RTPU_ARENA", None)  # the agent owns its *own* arena
        env.pop("RTPU_HOST_ID", None)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(cmd, env=env)
        self._agent_procs.append(proc)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            state = wc.client.request({"kind": "cluster_state"})
            # The head's own row can register concurrently with the agent:
            # it must never be mistaken for the node we just spawned.
            new = [n for n in state["nodes"]
                   if n["node_id"] not in before
                   and (n.get("labels") or {}).get("head") != "1"]
            if new:
                return new[0]["node_id"]
            if proc.poll() is not None:
                raise RuntimeError(
                    f"host agent exited rc={proc.returncode} before registering"
                )
            time.sleep(0.05)
        raise TimeoutError("host agent did not register within timeout")

    def kill_node_agent(self, index: int = 0) -> None:
        """Hard-kill a remote agent process (chaos testing: node failure)."""
        proc = self._agent_procs[index]
        proc.kill()
        proc.wait(timeout=5)

    def shutdown(self) -> None:
        api.shutdown()
        for proc in self._agent_procs:
            if proc.poll() is None:
                try:
                    proc.terminate()
                    proc.wait(timeout=3)
                except Exception:
                    try:
                        proc.kill()
                    except Exception:
                        pass
        self._agent_procs.clear()
