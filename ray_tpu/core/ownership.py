"""Distributed object ownership: per-owner refcounts and borrow tracking.

Role parity: the reference's per-worker ReferenceCounter
(/root/reference/src/ray/core_worker/reference_count.h:35 — owners track
local refs + borrower workers; borrowers report to the owner, not the GCS)
redesigned for this runtime's asyncio control plane. Every ref-count
mutation here is either process-local or a worker-to-worker message on the
owner's ref channel; the controller sees exactly ONE batched ``free_objects``
message per drained batch (the raylet-delete analog) and otherwise keeps
only the location directory.

Protocol (all fire-and-forget sends, FIFO-ordered per connection):

- ``ref_borrow_add {oid, borrower}``   first live handle in a borrowing
  process -> owner adds it to the borrower set.
- ``ref_borrow_drop {oid, borrower}``  last handle died -> owner removes it.
- ``ref_hold_add {oid, token}``        a submitter shipped a spec whose deps
  include this object: the object must outlive the in-flight spec even if
  every live handle dies (the classic submit-then-drop race).
- ``ref_hold_release {oid, token}``    the executing worker registered its
  own borrows (ordered BEFORE this release on the same connection), so the
  hold can go. Releases arriving before their add leave a tombstone.
- ``ref_locate {oid}``                 owner-side location fallback for a
  directory miss (reference: owned objects are resolved at the owner).

Premature-free safety argument: a spec's dep can only be freed when local
handles, borrowers and holds are ALL drained. The submitter either owns the
dep (local hold entry, no message) or borrows it (its ``hold_add`` rides the
same connection as — and therefore lands before — its eventual
``borrow_drop``); the executing worker's ``borrow_add`` precedes its
``hold_release`` on ITS connection. Any interleaving of the two connections
leaves at least one protector registered at all times.

Known v1 bounds (documented, both in the SAFE direction — objects can only
live too long, never too short):

- Refs NESTED inside a stored object's payload are pinned by the
  serializing process for that process's lifetime (see ``pin_nested``).
  The reference ties nested lifetime to the outer object's metadata; that
  refinement needs free-notification fan-out to producers.
- A borrower that DIES without draining leaves its token in the owner's
  borrower set, pinning the object until the owner process exits (the
  reference detects this via WaitForRefRemoved channel failure). Arena
  pressure still reclaims the bytes through the controller's spill/evict
  path, so the leak is directory metadata, not memory.
"""
from __future__ import annotations

import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu import flags

# ---------------------------------------------------------------------------
# per-process identity

_token = uuid.uuid4().hex[:16]
_lock = threading.RLock()
# Ref-server init only. NEVER the ref-table _lock: self_addr blocks on the
# io loop while the server starts, and the io loop's ref hooks
# (on_return_location et al.) take _lock — holding _lock across that wait
# deadlocks the loop until the io.call timeout fires and silently disables
# ownership for the whole process.
_addr_lock = threading.Lock()
_entries: Dict[str, "_Entry"] = {}
_pins: Dict[str, List[Any]] = {}  # outer oid -> nested ObjectRefs kept alive
_self_addr: Optional[str] = None  # "host:port|token" once a ref server runs
_conns: Dict[str, Any] = {}  # "host:port" -> protocol.Connection
_pending_free: List[Tuple[float, str]] = []  # (due time, oid)
_free_flush_scheduled = False
_alive = True  # flipped at interpreter teardown / shutdown
# Submit-holds this process placed, by token -> [(oid, owner_addr), ...].
_holds_out: Dict[str, List[Tuple[str, str]]] = {}
_return_to_token: Dict[str, str] = {}
# Census side-table: creating callsite per owned oid (RTPU_CALLSITE only —
# a separate dict so _Entry's __slots__ stay lean on the default path) and
# this process's human label ("driver" / "worker:<id8>") for owner
# attribution in `rtpu memory --group-by owner`.
_callsites: Dict[str, str] = {}
_proc_label: Optional[str] = None
_CALLSITES_MAX = 65536


class _Entry:
    __slots__ = ("local", "borrowers", "holds", "released_holds",
                 "owner_addr", "is_owner", "registered_borrow", "freed")

    def __init__(self) -> None:
        self.local = 0
        self.borrowers: Set[str] = set()
        self.holds: Set[str] = set()
        # token -> expiry. Tombstones exist only for the tiny
        # release-before-add race (two connections); they EXPIRE because
        # the common case (worker releases, then the submitter's
        # grace-delayed observation releases the same token again) would
        # otherwise pin one tombstone per task forever on hot objects.
        self.released_holds: Dict[str, float] = {}
        self.owner_addr = ""
        self.is_owner = False
        self.registered_borrow = False
        self.freed = False

    def drained(self) -> bool:
        return (self.local <= 0 and not self.borrowers and not self.holds)

    def tombstone(self, token: str) -> None:
        now = time.monotonic()
        if len(self.released_holds) > 32:
            self.released_holds = {t: exp for t, exp in
                                   self.released_holds.items() if exp > now}
        self.released_holds[token] = now + _TOMBSTONE_TTL_S


_TOMBSTONE_TTL_S = 120.0


def process_token() -> str:
    return _token


def set_process_label(label: str) -> None:
    """Name this process for census owner attribution (workers call it at
    startup with "worker:<id8>"; drivers default to "driver")."""
    global _proc_label
    _proc_label = label


def process_label() -> str:
    return _proc_label or "driver"


def _capture_callsite() -> Optional[str]:
    """First stack frame outside ray_tpu, as "file:line" (reference:
    RAY_record_ref_creation_sites). Called only under RTPU_CALLSITE."""
    try:
        f = sys._getframe(2)
        while f is not None:
            fn = f.f_code.co_filename
            if "ray_tpu" not in fn.replace("\\", "/"):
                return f"{fn}:{f.f_lineno}"
            f = f.f_back
    except Exception:
        pass
    return None


def _record_callsite_locked(oid: str, cs: Optional[str]) -> None:
    if not cs:
        return
    if len(_callsites) >= _CALLSITES_MAX:
        _callsites.pop(next(iter(_callsites)), None)
    _callsites[oid] = cs


def enabled() -> bool:
    return bool(flags.get("RTPU_DISTRIBUTED_REFS"))


# ---------------------------------------------------------------------------
# ref server (the owner's channel)


def set_self_addr(host: str, port: int) -> None:
    """Workers: reuse the direct-dispatch server as the ref channel."""
    global _self_addr
    _self_addr = f"{host}:{port}|{_token}"


def self_addr() -> str:
    """This process's owner address, starting the ref server if needed."""
    global _self_addr
    if _self_addr is not None:
        return _self_addr
    with _addr_lock:
        if _self_addr is not None:
            return _self_addr
        if not enabled():
            _self_addr = ""
            return ""
        try:
            _self_addr = _start_ref_server()
        except Exception:
            _self_addr = ""  # ownership degrades to never-free, never breaks
    return _self_addr


def _start_ref_server() -> str:
    """Driver-side ref server on the client's existing io loop."""
    from . import context as ctx
    from . import protocol

    wc = ctx.get_worker_context()

    async def serve():
        import asyncio

        async def on_conn(reader, writer):
            conn = protocol.Connection(
                reader, writer, handler=_handle_async, name="refsrv")
            conn.start()

        try:
            bind = wc.client.conn.writer.get_extra_info("sockname")[0]
        except Exception:
            bind = "127.0.0.1"
        return await asyncio.start_server(on_conn, bind, 0)

    server = wc.client.io.call(serve(), timeout=10)
    host, port = server.sockets[0].getsockname()[:2]
    return f"{host}:{port}|{_token}"


async def _handle_async(conn, msg):
    if msg.get("kind", "").startswith("pull_"):
        # The ref server doubles as this process's pull server: a driver's
        # put objects are served to remote consumers straight from here
        # (same producer-serving contract as the worker direct server).
        from . import transfer

        return await transfer.handle_pull_server_message(conn, msg)
    return handle_ref_message(msg)


def handle_ref_message(msg: Dict[str, Any]) -> Any:
    """Dispatch one ref_* message (called from any server's handler)."""
    kind = msg["kind"]
    oid = msg["oid"]
    with _lock:
        e = _entries.get(oid)
        if kind == "ref_borrow_add":
            if e is None:
                e = _entries.setdefault(oid, _Entry())
            e.borrowers.add(msg["borrower"])
            return None
        if kind == "ref_borrow_add_batch":
            for o in oid:  # oid is a list for batch kinds
                be = _entries.get(o) or _entries.setdefault(o, _Entry())
                be.borrowers.add(msg["borrower"])
            return None
        if kind == "ref_hold_release_batch":
            tok = msg["token"]
            for o in oid:
                be = _entries.get(o) or _entries.setdefault(o, _Entry())
                if tok in be.holds:
                    be.holds.discard(tok)
                    _maybe_free_locked(o, be)
                    _reap_zombie_locked(o, be)
                else:
                    be.tombstone(tok)
            return None
        if kind == "ref_borrow_drop":
            if e is not None:
                e.borrowers.discard(msg["borrower"])
                _maybe_free_locked(oid, e)
                _reap_zombie_locked(oid, e)
            return None
        if kind == "ref_hold_add":
            if e is None:
                e = _entries.setdefault(oid, _Entry())
            tok = msg["token"]
            if tok in e.released_holds:
                e.released_holds.pop(tok, None)  # release won the race
            else:
                e.holds.add(tok)
            return None
        if kind == "ref_hold_release":
            if e is None:
                e = _entries.setdefault(oid, _Entry())
            tok = msg["token"]
            if tok in e.holds:
                e.holds.discard(tok)
                _maybe_free_locked(oid, e)
                _reap_zombie_locked(oid, e)
            else:
                e.tombstone(tok)
            return None
    if kind == "ref_locate":
        from . import api

        loc = api._local_locs.get(oid)
        return {"loc": loc}
    if kind == "ref_locate_batch":
        from . import api

        return {"locs": {o: api._local_locs.get(o) for o in oid}}
    return None


# ---------------------------------------------------------------------------
# sending to owners


def _parse(addr: str) -> Tuple[str, str]:
    hostport, _, tok = addr.partition("|")
    return hostport, tok


def _conn_to(hostport: str):
    from . import context as ctx
    from . import protocol

    conn = _conns.get(hostport)
    if conn is not None and not conn.closed.is_set():
        return conn
    wc = ctx.get_worker_context()
    host, _, port = hostport.rpartition(":")
    conn = wc.client.io.call(
        protocol.connect(host, int(port), name=f"refs->{hostport}"),
        timeout=5)
    _conns[hostport] = conn
    return conn


_send_q: "Optional[Any]" = None
_sender_started = False


def _send_to_owner(owner_addr: str, msg: Dict[str, Any]) -> bool:
    """Fire-and-forget, FIFO per owner (single sender thread drains one
    queue, so per-owner order is the enqueue order). Enqueue-only from the
    caller's perspective: ref hooks fire on arbitrary threads — including
    the io loop, where a blocking connect would deadlock. Unreachable
    owners are dropped (a dead owner's objects are GC'd with it)."""
    global _send_q, _sender_started
    hostport, tok = _parse(owner_addr)
    if tok == _token:
        handle_ref_message(msg)  # self-send: mutate locally
        return True
    with _lock:
        if _send_q is None:
            import queue

            _send_q = queue.Queue()
        if not _sender_started:
            _sender_started = True
            threading.Thread(target=_sender_loop, daemon=True,
                             name="ref-sender").start()
    _send_q.put((hostport, msg))
    return True


def _sender_loop() -> None:
    from . import context as ctx

    while _alive:
        try:
            hostport, msg = _send_q.get(timeout=5)
        except Exception:
            continue
        try:
            wc = ctx.get_worker_context()
            conn = _conn_to(hostport)
            wc.client.io.call_nowait(conn.send(msg))
        except Exception:
            _conns.pop(hostport, None)  # owner gone: drop its queue tail too


# ---------------------------------------------------------------------------
# handle-count hooks (ObjectRef.__init__ / __del__)


def on_ref_created(oid: str, owner_addr: str) -> None:
    if not _alive or not enabled():
        return
    try:
        with _lock:
            e = _entries.get(oid)
            if e is None:
                e = _entries.setdefault(oid, _Entry())
            e.local += 1
            if owner_addr and not e.owner_addr:
                e.owner_addr = owner_addr
            need_register = (
                not e.is_owner and not e.registered_borrow and e.owner_addr
                and _parse(e.owner_addr)[1] != _token)
            if need_register:
                e.registered_borrow = True
        if need_register:
            _send_to_owner(e.owner_addr, {
                "kind": "ref_borrow_add", "oid": oid, "borrower": _token})
    except Exception:
        pass  # ref accounting must never break user code


def on_ref_deleted(oid: str) -> None:
    if not _alive or not enabled():
        return
    try:
        with _lock:
            e = _entries.get(oid)
            if e is None:
                return
            e.local -= 1
            if e.local > 0:
                return
            if e.is_owner:
                _maybe_free_locked(oid, e)
                return
            registered = e.registered_borrow
            owner = e.owner_addr
            _entries.pop(oid, None)
        if registered and owner:
            _send_to_owner(owner, {
                "kind": "ref_borrow_drop", "oid": oid, "borrower": _token})
    except Exception:
        pass


def claim_ownership(oid: str, loc: Any = None) -> None:
    """Mark this process the owner of `oid` (put() and task-return sites
    call this BEFORE constructing the first ObjectRef)."""
    if not enabled():
        return
    addr = self_addr()
    cs = _capture_callsite() if flags.get("RTPU_CALLSITE") else None
    with _lock:
        e = _entries.get(oid)
        if e is None:
            e = _entries.setdefault(oid, _Entry())
        e.is_owner = True
        e.owner_addr = addr or ""
        _record_callsite_locked(oid, cs)


def claim_return_refs(oids) -> str:
    """Task-return fast path: ONE lock round claims ownership of every
    return id AND counts its first local handle. The caller constructs the
    ObjectRefs via __new__ (api._claim_return_refs), skipping __init__'s
    on_ref_created — its whole effect for a self-owned fresh id (local+=1,
    owner_addr set, no borrow registration) happens here. Returns this
    process's owner address for the handles."""
    if not _alive or not enabled():
        return ""
    addr = self_addr() or ""
    cs = _capture_callsite() if flags.get("RTPU_CALLSITE") else None
    with _lock:
        for oid in oids:
            e = _entries.get(oid)
            if e is None:
                e = _entries.setdefault(oid, _Entry())
            e.is_owner = True
            e.owner_addr = addr
            e.local += 1
            _record_callsite_locked(oid, cs)
    return addr


def owner_addr_for(oid: str) -> str:
    with _lock:
        e = _entries.get(oid)
        return e.owner_addr if e is not None else ""


# ---------------------------------------------------------------------------
# submit-holds (spec in flight keeps its deps alive)


def register_submit_holds(token: str, deps: List[str],
                          return_ids: List[str]) -> Dict[str, str]:
    """Called by the submitter at pack time. Returns {oid: owner_addr} for
    the spec (``dep_owners``). Owned deps get a local hold; borrowed deps
    get a ``hold_add`` to their owner (same connection as the future
    ``borrow_drop`` -> ordered)."""
    if not enabled():
        return {}
    dep_owners: Dict[str, str] = {}
    placed: List[Tuple[str, str]] = []
    for oid in deps:
        with _lock:
            e = _entries.get(oid)
            if e is None:
                continue
            owner = e.owner_addr
            if not owner:
                continue
            dep_owners[oid] = owner
            if e.is_owner:
                if token in e.released_holds:
                    e.released_holds.pop(token, None)
                else:
                    e.holds.add(token)
                placed.append((oid, ""))
                continue
        if _send_to_owner(owner, {"kind": "ref_hold_add", "oid": oid,
                                  "token": token}):
            placed.append((oid, owner))
    stale: List[Tuple[str, List[Tuple[str, str]]]] = []
    if placed:
        with _lock:
            _holds_out[token] = placed
            for rid in return_ids:
                _return_to_token[rid] = token
            # Bound the registries: tasks whose outcome this process never
            # observes (fire-and-forget, result never fetched) would pin
            # their deps forever. Evicting the OLDEST submissions releases
            # holds for specs that have long since dispatched (the worker's
            # own borrow has taken over by then).
            while len(_holds_out) > 8192:
                t = next(iter(_holds_out))
                stale.append((t, _holds_out.pop(t)))
            while len(_return_to_token) > 32768:
                _return_to_token.pop(next(iter(_return_to_token)), None)
    for tok, spl in stale:
        _release_placed(tok, spl)
    return dep_owners


def release_submit_holds(token: str) -> None:
    """Submitter-side release — used when the submitter OBSERVES the task's
    outcome (direct-completion callback, or a return-oid location/error
    arriving), covering specs that died before any worker saw them."""
    if not enabled():
        return
    with _lock:
        placed = _holds_out.pop(token, None)
    if placed:
        _release_placed(token, placed)


def _release_placed(token: str, placed: List[Tuple[str, str]]) -> None:
    for oid, owner in placed:
        if owner == "":
            with _lock:
                e = _entries.get(oid)
                if e is not None:
                    if token in e.holds:
                        e.holds.discard(token)
                        _maybe_free_locked(oid, e)
                    else:
                        e.tombstone(token)
        else:
            _send_to_owner(owner, {"kind": "ref_hold_release", "oid": oid,
                                   "token": token})


_pending_hold_release: List[Tuple[float, str]] = []  # (due time, token)
_hold_release_scheduled = False


def on_return_location(oid: str) -> None:
    """A task-return location (or error) became visible locally.

    The release is DELAYED by a grace window: the executing worker's own
    hold_release is ordered after its borrow_add on the owner connection,
    but this locally-observed release has no such ordering — firing it
    immediately lets `submit; get(); del ref` free the object before the
    worker's in-flight borrow_add lands (measured race: an actor storing a
    ref it was handed lost the object when the caller dropped its handle
    right after the call returned). Each token carries its OWN deadline —
    a shared sleep-once batch would give ~zero grace to tokens observed
    near the end of the window."""
    global _hold_release_scheduled
    if not enabled():
        return
    with _lock:
        token = _return_to_token.pop(oid, None)
        if token is None:
            return
        due = time.monotonic() + float(flags.get("RTPU_HOLD_RELEASE_GRACE_S"))
        _pending_hold_release.append((due, token))
        if _hold_release_scheduled:
            return
        _hold_release_scheduled = True
    threading.Thread(target=_hold_release_pump, daemon=True,
                     name="ref-hold-release").start()


def on_return_locations(oids) -> None:
    """Batch form of on_return_location: one lock round for a whole batched
    direct reply (it runs on the client's io thread — per-oid locking there
    taxes every submitting thread through the GIL)."""
    global _hold_release_scheduled
    if not enabled():
        return
    start_pump = False
    with _lock:
        if not _return_to_token:
            return
        due = None
        for oid in oids:
            token = _return_to_token.pop(oid, None)
            if token is None:
                continue
            if due is None:
                due = time.monotonic() + float(
                    flags.get("RTPU_HOLD_RELEASE_GRACE_S"))
            _pending_hold_release.append((due, token))
        if due is not None and not _hold_release_scheduled:
            _hold_release_scheduled = True
            start_pump = True
    if start_pump:
        threading.Thread(target=_hold_release_pump, daemon=True,
                         name="ref-hold-release").start()


def _hold_release_pump() -> None:
    """Drain (due, token) entries as each deadline passes; exits when the
    queue empties (a later enqueue starts a fresh pump)."""
    global _hold_release_scheduled
    while _alive:
        with _lock:
            if not _pending_hold_release:
                _hold_release_scheduled = False
                return
            due, token = _pending_hold_release[0]
            wait = due - time.monotonic()
            if wait <= 0:
                _pending_hold_release.pop(0)
                token_ready = token
            else:
                token_ready = None
        if token_ready is None:
            time.sleep(min(wait, 0.5))
        else:
            release_submit_holds(token_ready)


# ---------------------------------------------------------------------------
# executing-worker side


def acquire_spec_refs(spec: Dict[str, Any]) -> List[Any]:
    """Register this process as borrower of every dep, THEN release the
    submitter's holds (FIFO on the owner connection makes the borrows land
    first). ONE borrow_add_batch + ONE hold_release_batch per distinct
    owner — a 1000-dep fan-in task costs two messages per owner, not 2000
    (measured: the per-dep version put fanin_1000_refs at 0.28s vs 0.01).
    Returns the handle list; keep it alive until the completion report is
    sent, then just drop it."""
    if not enabled():
        return []
    dep_owners: Dict[str, str] = spec.get("dep_owners") or {}
    if not dep_owners:
        return []
    from .serialization import ObjectRef

    token = spec.get("task_id", "")
    by_owner: Dict[str, List[str]] = {}
    with _lock:
        for oid, owner in dep_owners.items():
            if _parse(owner)[1] == _token:
                continue  # self-owned: the handle below is protection enough
            e = _entries.get(oid) or _entries.setdefault(oid, _Entry())
            if not e.owner_addr:
                e.owner_addr = owner
            if not e.registered_borrow:
                # Mark BEFORE constructing handles so ObjectRef.__init__
                # doesn't send per-oid adds; the batch below covers them.
                e.registered_borrow = True
                by_owner.setdefault(owner, []).append(oid)
    for owner, oids in by_owner.items():
        _send_to_owner(owner, {"kind": "ref_borrow_add_batch", "oid": oids,
                               "borrower": _token})
    held = [ObjectRef(oid, owner) for oid, owner in dep_owners.items()]
    rel_by_owner: Dict[str, List[str]] = {}
    for oid, owner in dep_owners.items():
        rel_by_owner.setdefault(owner, []).append(oid)
    for owner, oids in rel_by_owner.items():
        _send_to_owner(owner, {"kind": "ref_hold_release_batch",
                               "oid": oids, "token": token})
    return held


# ---------------------------------------------------------------------------
# nested refs


def locate_from_owner(oid: str, owner_addr: str,
                      timeout: float = 3.0) -> Optional[Any]:
    """Ask the owner for the object's location (blocking; task threads
    only). None on any failure — callers fall back to the controller."""
    out = locate_from_owner_batch([oid], owner_addr, timeout=timeout)
    return out.get(oid)


def locate_from_owner_batch(oids: List[str], owner_addr: str,
                            timeout: float = 3.0) -> Dict[str, Any]:
    """One round-trip for ALL of an owner's deps (a per-dep loop would
    serialize K blocking RPCs — and K timeouts when the owner is dead).
    Empty dict on any failure: callers fall back to one batched
    controller get_locations."""
    if not enabled() or not oids:
        return {}
    try:
        hostport, tok = _parse(owner_addr)
        if tok == _token:
            from . import api

            return {o: api._local_locs.get(o) for o in oids}
        conn = _conn_to(hostport)
        res = conn.request_threadsafe(
            {"kind": "ref_locate_batch", "oid": list(oids)}).result(timeout)
        return {o: loc for o, loc in ((res or {}).get("locs") or {}).items()
                if loc is not None}
    except Exception:
        return {}


def pin_nested(outer_oid: str, refs: List[Any]) -> None:
    """Keep refs discovered inside a serialized payload alive in this
    process (v1 bound: for the process lifetime — see module docstring)."""
    if refs and enabled():
        with _lock:
            _pins.setdefault(outer_oid, []).extend(refs)


# ---------------------------------------------------------------------------
# freeing


def _reap_zombie_locked(oid: str, e: "_Entry") -> None:
    """Drop drained NON-owner entries resurrected by late borrow/hold
    messages (e.g. a borrow_add landing after the owner freed the object) —
    they can never free anything and would otherwise accumulate."""
    cur = _entries.get(oid)
    if (cur is e and not e.is_owner and e.drained()
            and not e.registered_borrow and not e.released_holds):
        _entries.pop(oid, None)


def _maybe_free_locked(oid: str, e: "_Entry") -> None:
    """Caller holds _lock. Schedule the terminal free for a drained entry."""
    global _free_flush_scheduled
    if not e.is_owner or e.freed or not e.drained():
        return
    e.freed = True
    _entries.pop(oid, None)
    _pins.pop(oid, None)
    _callsites.pop(oid, None)
    due = time.monotonic() + float(flags.get("RTPU_FREE_DELAY_S"))
    _pending_free.append((due, oid))
    if not _free_flush_scheduled:
        _free_flush_scheduled = True
        threading.Thread(target=_free_pump, daemon=True,
                         name="ref-free").start()


def _free_pump() -> None:
    """Per-oid grace (a shared sleep would shortchange late arrivals), but
    everything whose deadline has passed ships in ONE batched
    fire-and-forget free_objects — the single controller message of the
    whole ref lifecycle (raylet-delete parity)."""
    global _free_flush_scheduled
    while _alive:
        with _lock:
            if not _pending_free:
                _free_flush_scheduled = False
                return
            # Entries are appended with a constant grace, so the list is
            # due-ordered: take the due prefix and keep the rest. (The old
            # full-list double scan here ran under the global ref lock on
            # every trickle of frees — during a submission wave that was a
            # continuous O(pending) tax on the lock every hot-path ref op
            # needs.)
            now = time.monotonic()
            i = 0
            n = len(_pending_free)
            while i < n and _pending_free[i][0] <= now:
                i += 1
            batch = [oid for _, oid in _pending_free[:i]]
            del _pending_free[:i]
            wait = 0.0 if batch else _pending_free[0][0] - now
        if not batch:
            time.sleep(min(max(wait, 0.01), 0.5))
            continue
        try:
            from . import api
            from . import context as ctx

            wc = ctx.get_worker_context()
            for oid in batch:
                api._local_locs.pop(oid, None)
            wc.client.io.call_nowait(wc.client.conn.send(
                {"kind": "free_objects", "object_ids": batch}))
        except Exception:
            pass


import atexit


@atexit.register
def _mark_dead() -> None:
    global _alive
    _alive = False


def shutdown() -> None:
    """Reset per-process state (init/shutdown cycles in one process)."""
    global _self_addr, _free_flush_scheduled, _hold_release_scheduled
    with _lock:
        _entries.clear()
        _pins.clear()
        _callsites.clear()
        _holds_out.clear()
        _return_to_token.clear()
        _pending_free.clear()
        _pending_hold_release.clear()
        _free_flush_scheduled = False
        _hold_release_scheduled = False
        for conn in _conns.values():
            try:
                conn.closed.set()
            except Exception:
                pass
        _conns.clear()
        _self_addr = None


def stats() -> Dict[str, int]:
    with _lock:
        return {
            "entries": len(_entries),
            "owned": sum(1 for e in _entries.values() if e.is_owner),
            "borrowed": sum(1 for e in _entries.values()
                            if e.registered_borrow),
            "pins": len(_pins),
            "holds_out": len(_holds_out),
        }


def census_shard(max_entries: int = 20000) -> Dict[str, Any]:
    """This process's rows for the cluster object census (`rtpu memory`).

    Size and storage tier are resolved lazily at census time from the
    process-local location cache (api._local_locs) instead of being
    recorded per ref at creation — the put/return hot paths pay nothing
    for the census beyond the optional RTPU_CALLSITE stack walk. Rows the
    local cache can't size are still reported (the controller's directory
    fills size/tier in for them during aggregation)."""
    if not flags.get("RTPU_CENSUS"):
        return {"disabled": True, "label": process_label(),
                "token": _token, "rows": []}
    with _lock:
        items = list(_entries.items())
        truncated = max(0, len(items) - max_entries)
        items = items[:max_entries]
        pin_counts = {o: len(v) for o, v in _pins.items()}
        callsites = dict(_callsites)
    try:
        from . import api

        local_locs = api._local_locs
    except Exception:
        local_locs = {}
    rows: List[Dict[str, Any]] = []
    for oid, e in items:
        loc = local_locs.get(oid)
        size = int(getattr(loc, "size", 0) or 0)
        tier = ""
        if loc is not None:
            try:
                from . import object_store

                tier = object_store.storage_kind(loc)
            except Exception:
                tier = ""
        rows.append({
            "oid": oid,
            "owned": e.is_owner,
            "local": e.local,
            "borrowers": len(e.borrowers),
            "holds": len(e.holds),
            "pins": pin_counts.get(oid, 0),
            "size": size,
            "tier": tier,
            "callsite": callsites.get(oid),
        })
    return {"label": process_label(), "token": _token, "rows": rows,
            "truncated": truncated, "t": time.time()}
