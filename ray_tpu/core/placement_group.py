"""Placement groups: gang reservation of resource bundles.

Reference: python/ray/util/placement_group.py + the GCS-side 2-phase scheduler
(src/ray/gcs/gcs_server/gcs_placement_group_scheduler.h:274). Strategies:
PACK / SPREAD / STRICT_PACK / STRICT_SPREAD over virtual nodes. On TPU this is
the primitive that reserves a *slice*: one bundle per TPU host, STRICT_SPREAD
across hosts, then the mesh layer forms a jax Mesh on the reserved hosts.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from . import context as ctx
from .ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


@dataclass
class PlacementGroup:
    id: str
    bundle_specs: List[Dict[str, float]]
    strategy: str

    def ready(self, timeout: Optional[float] = None) -> bool:
        wc = ctx.get_worker_context()
        info = wc.client.request({"kind": "pg_wait", "pg_id": self.id, "timeout": timeout})
        return info["state"] == "ready"

    def wait(self, timeout: Optional[float] = None) -> bool:
        try:
            return self.ready(timeout)
        except Exception:
            return False

    def bundle_nodes(self) -> List[str]:
        wc = ctx.get_worker_context()
        info = wc.client.request({"kind": "pg_wait", "pg_id": self.id, "timeout": None})
        return info["bundle_nodes"]

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be non-empty resource dicts")
    wc = ctx.get_worker_context()
    pg_id = PlacementGroupID.generate()
    wc.client.request(
        {
            "kind": "create_placement_group",
            "pg_id": pg_id,
            "bundles": [dict(b) for b in bundles],
            "strategy": strategy,
            "name": name,
        }
    )
    return PlacementGroup(pg_id, [dict(b) for b in bundles], strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    wc = ctx.get_worker_context()
    wc.client.request({"kind": "remove_placement_group", "pg_id": pg.id})
