"""Worker process runtime: task execution loop + actor hosting.

Role-equivalent to the reference's worker-side CoreWorker + the Python worker
shell (ray: src/ray/core_worker/core_worker.cc ExecuteTask path,
python/ray/_private/workers/default_worker.py). One OS process per worker;
plain tasks run on a small thread pool, each actor gets a dedicated mailbox
thread providing ordered execution (max_concurrency>1 widens the mailbox to a
thread pool, mirroring threaded actors / ConcurrencyGroupManager).

Workers import neither jax nor any ML library at startup — a worker stays a
~50ms-spawn control-plane process until user code pulls heavy imports.
"""
from __future__ import annotations

import os
import pickle
import queue
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import cloudpickle

from . import context as ctx
from .client import CoreClient
from .controller import ActorDiedError, TaskError
from .ids import WorkerID
from .object_store import ObjectLocation, get_bytes, put_bytes
from .serialization import ArgRef, ObjectRef


class ActorMailbox:
    """Ordered (or bounded-concurrency) execution context for one actor."""

    def __init__(self, runtime: "WorkerRuntime", actor_id: str, max_concurrency: int):
        self.runtime = runtime
        self.actor_id = actor_id
        self.instance: Any = None
        self.q: "queue.Queue[Optional[Dict[str, Any]]]" = queue.Queue()
        self.threads = [
            threading.Thread(target=self._loop, name=f"actor-{actor_id[:8]}-{i}", daemon=True)
            for i in range(max(1, max_concurrency))
        ]
        for t in self.threads:
            t.start()

    def submit(self, spec: Dict[str, Any]) -> None:
        self.q.put(spec)

    def stop(self) -> None:
        for _ in self.threads:
            self.q.put(None)

    def _loop(self) -> None:
        while True:
            spec = self.q.get()
            if spec is None:
                return
            if "__create__" in spec:
                spec["__create__"]()
                continue
            self.runtime.run_task(spec, actor_instance=self.instance)


class WorkerRuntime:
    def __init__(self, controller_addr: str, node_id: str):
        host, port = controller_addr.rsplit(":", 1)
        self.worker_id = WorkerID.generate()
        self.node_id = node_id
        self.client = CoreClient(host, int(port), handler=self._handle)
        self.pool = ThreadPoolExecutor(max_workers=32, thread_name_prefix="task")
        self.functions: Dict[str, Any] = {}
        self.actors: Dict[str, ActorMailbox] = {}
        self.shutdown_event = threading.Event()
        # Context must be live before registration: the controller may push a
        # task the instant the register request lands.
        ctx.set_worker_context(ctx.WorkerContext(client=self.client, node_id=node_id, role="worker"))
        self.client.request(
            {
                "kind": "register",
                "role": "worker",
                "worker_id": self.worker_id,
                "node_id": node_id,
                "spawn_token": os.environ.get("RTPU_SPAWN_TOKEN"),
                "tpu_capable": bool(os.environ.get("RTPU_TPU_WORKER")),
            }
        )

        # Fate-share with the controller: if the control connection drops the
        # worker must die (reference: workers fate-share with their raylet;
        # an orphaned worker would leak forever).
        async def _watch_conn() -> None:
            await self.client.conn.closed.wait()
            self.shutdown_event.set()

        self.client.io.call_nowait(_watch_conn())

    # ----------------------------------------------------------- push handler

    async def _handle(self, conn, msg):
        kind = msg["kind"]
        if kind == "execute_task":
            self.pool.submit(self.run_task, msg["spec"])
        elif kind == "instantiate_actor":
            self._instantiate_actor(msg["spec"])
        elif kind == "execute_actor_task":
            spec = msg["spec"]
            mb = self.actors.get(spec["actor_id"])
            if mb is not None:
                mb.submit(spec)
        elif kind == "shutdown":
            self.shutdown_event.set()
        elif kind == "pubsub":
            ctx.deliver_pubsub(msg["channel"], msg["data"])
        return None

    # -------------------------------------------------------------- execution

    def _load_function(self, func_id: str) -> Any:
        fn = self.functions.get(func_id)
        if fn is None:
            blob = self.client.request({"kind": "fetch_function", "func_id": func_id})
            fn = cloudpickle.loads(blob)
            self.functions[func_id] = fn
        return fn

    def _resolve_args(self, spec: Dict[str, Any]) -> tuple:
        args, kwargs = pickle.loads(spec["args_blob"])
        ref_ids = [v.object_id for v in (*args, *kwargs.values()) if isinstance(v, ArgRef)]
        locs: Dict[str, ObjectLocation] = {}
        if ref_ids:
            locs = self.client.request({"kind": "get_locations", "object_ids": ref_ids})

        def resolve(v: Any) -> Any:
            if isinstance(v, ArgRef):
                loc = locs[v.object_id]
                val = get_bytes(loc)
                if loc.is_error:
                    raise val if isinstance(val, BaseException) else RuntimeError(val)
                return val
            return v

        args = tuple(resolve(a) for a in args)
        kwargs = {k: resolve(v) for k, v in kwargs.items()}
        return args, kwargs

    def run_task(self, spec: Dict[str, Any], actor_instance: Any = None) -> None:
        task_id = spec["task_id"]
        tls = ctx.task_local
        tls.task_id = task_id
        tls.label = spec.get("label", "")
        try:
            args, kwargs = self._resolve_args(spec)
            if spec.get("actor_id") and actor_instance is not None:
                method = getattr(actor_instance, spec["method_name"])
                result = method(*args, **kwargs)
            else:
                fn = self._load_function(spec["func_id"])
                result = fn(*args, **kwargs)
            if _is_coroutine(result):
                import asyncio

                result = asyncio.run(result)
            locations = self._store_returns(spec, result)
            self.client.request(
                {
                    "kind": "task_done",
                    "task_id": task_id,
                    "worker_id": self.worker_id,
                    "locations": locations,
                }
            )
        except BaseException as e:  # noqa: BLE001 — every task error is captured
            tb = traceback.format_exc()
            label = spec.get("label", task_id[:8])
            err = TaskError(label, e, tb)
            try:
                data = pickle.dumps(err)
            except Exception:
                # Unpicklable cause (socket, lock, ...): degrade to a string
                # rendition so the error still reaches the caller instead of
                # hanging the task forever.
                err = TaskError(label, RuntimeError(f"{type(e).__name__}: {e}"), tb)
                data = pickle.dumps(err)
            err_locs = [
                ObjectLocation(object_id=oid, size=len(data), inline=data, is_error=True)
                for oid in spec["return_ids"]
            ]
            try:
                self.client.request(
                    {
                        "kind": "task_done",
                        "task_id": task_id,
                        "worker_id": self.worker_id,
                        "error_locations": err_locs,
                    }
                )
            except Exception:
                pass
        finally:
            tls.task_id = None

    def _store_returns(self, spec: Dict[str, Any], result: Any) -> List[ObjectLocation]:
        return_ids: List[str] = spec["return_ids"]
        if not return_ids:
            return []
        if len(return_ids) == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != len(return_ids):
                raise ValueError(
                    f"task declared num_returns={len(return_ids)} but returned {len(values)}"
                )
        return [put_bytes(v, oid, self.node_id) for v, oid in zip(values, return_ids)]

    def _instantiate_actor(self, spec: Dict[str, Any]) -> None:
        actor_id = spec["actor_id"]
        mb = ActorMailbox(self, actor_id, spec.get("max_concurrency", 1))
        self.actors[actor_id] = mb

        def create():
            try:
                cls = self._load_function(spec["func_id"])
                args, kwargs = self._resolve_args(spec)
                mb.instance = cls(*args, **kwargs)
                ctx.task_local.actor_id = actor_id
                self.client.request({"kind": "actor_ready", "actor_id": actor_id})
            except BaseException as e:  # noqa: BLE001
                tb = traceback.format_exc()
                self.client.request(
                    {
                        "kind": "actor_error",
                        "actor_id": actor_id,
                        "error": ActorDiedError(f"actor constructor failed: {e!r}\n{tb}"),
                    }
                )

        # __init__ runs on the mailbox thread so actor state is thread-affine.
        mb.q.put({"__create__": create})

    def serve_forever(self) -> None:
        self.shutdown_event.wait()
        try:
            self.client.close()
        except Exception:
            pass
        # Hard-exit: executor threads are non-daemon and user task code may be
        # mid-flight; a worker told to shut down must actually die (the
        # reference's raylet SIGTERMs its workers for the same reason).
        os._exit(0)


def _is_coroutine(x: Any) -> bool:
    import inspect

    return inspect.iscoroutine(x)
