"""Worker process runtime: task execution loop + actor hosting.

Role-equivalent to the reference's worker-side CoreWorker + the Python worker
shell (ray: src/ray/core_worker/core_worker.cc ExecuteTask path,
python/ray/_private/workers/default_worker.py). One OS process per worker;
plain tasks run on a small thread pool, each actor gets a dedicated mailbox
thread providing ordered execution (max_concurrency>1 widens the mailbox to a
thread pool, mirroring threaded actors / ConcurrencyGroupManager).

Workers import neither jax nor any ML library at startup — a worker stays a
~50ms-spawn control-plane process until user code pulls heavy imports.
"""
from __future__ import annotations

from ray_tpu import flags

import os
import pickle
import queue
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import cloudpickle

from . import context as ctx
from . import task_events
from .client import CoreClient
from .controller import ActorDiedError, ActorNotHostedError, TaskError
from .ids import WorkerID
from .object_store import (ObjectLocation, get_bytes, get_bytes_with_refresh,
                           put_bytes)
from .serialization import ArgRef, ObjectRef


class ActorExitSignal(BaseException):
    """Raised by ray_tpu.exit_actor inside an actor method: the call
    completes with None and the actor shuts down intentionally (no
    restart, queued calls fail with ActorDiedError)."""


def exit_actor() -> None:
    """Reference: ray.actor.exit_actor — terminate the hosting actor after
    the current call. Only valid inside an actor method."""
    from . import context as _ctx

    if _ctx.current_actor_id() is None:
        raise RuntimeError("exit_actor() called outside an actor method")
    raise ActorExitSignal()


class ActorMailbox:
    """Ordered (or bounded-concurrency) execution context for one actor.

    Actors whose classes define ``async def`` methods additionally get a
    persistent asyncio event loop on its own thread: coroutine methods are
    scheduled there and genuinely interleave while awaiting (reference:
    async actors on a per-actor eventloop, core_worker/fiber.h + ray's
    AsyncioActor; the round-1 per-call asyncio.run() serialized them)."""

    def __init__(self, runtime: "WorkerRuntime", actor_id: str, max_concurrency: int):
        self.runtime = runtime
        self.actor_id = actor_id
        self.instance: Any = None
        self.spec: Optional[Dict[str, Any]] = None  # creation spec (re-claim)
        # SimpleQueue: C-implemented put/get, no per-op lock dance — the
        # mailbox hop is on every actor call's critical path.
        self.q: "queue.SimpleQueue[Optional[Dict[str, Any]]]" = \
            queue.SimpleQueue()
        self.exited = False  # exit_actor ran: refuse everything queued
        # Per-caller sequence reordering state: caller -> {next, held}.
        self._seq: Dict[str, Dict[str, Any]] = {}
        self._seq_lock = threading.Lock()
        # Crash-consistent fault tolerance (core/checkpoint.py): durable
        # checkpoint cadence + the exactly-once replay journal. Configured
        # from the creation spec via configure(); all off by default so a
        # plain actor pays nothing.
        self.ckpt_every_n = 0
        self.ckpt_interval = 0.0
        self.ckpt_enabled = False
        self.replay = False          # journal (caller, seqno) -> result
        self.ckpt_epoch = 0
        self.calls_since_ckpt = 0
        self.last_ckpt = time.monotonic()
        self._ckpt_pending = False
        # caller -> {seqno: result payload} of APPLIED calls; a retried
        # (caller, seqno) short-circuits to its recorded payload instead of
        # re-executing (reference: the dedup the per-handle sequence_no of
        # direct_actor_task_submitter enables). Bounded per caller.
        self.journal: Dict[str, Dict[int, Dict[str, Any]]] = {}
        self._inflight_keys: set = set()   # accepted, not yet journaled
        self._dup_waiters: Dict[tuple, List[Dict[str, Any]]] = {}
        self.aio_loop: Any = None  # created lazily for async actors
        self.aio_sem: Any = None
        self._aio_lock = threading.Lock()
        self.max_concurrency = max(1, max_concurrency)
        self.threads = [
            threading.Thread(target=self._loop, name=f"actor-{actor_id[:8]}-{i}", daemon=True)
            for i in range(self.max_concurrency)
        ]
        for t in self.threads:
            t.start()

    # How long a sequence gap may stall later calls before they flush
    # anyway (the missing call may have failed permanently en route, or
    # this actor restarted and joined the caller's sequence mid-stream).
    _SEQ_GAP_TIMEOUT_S = 1.0

    # Journal entries retained per caller (seqnos are dense per handle, so
    # this bounds dedup memory to the retry horizon, not actor lifetime).
    _JOURNAL_MAX = 1024

    def configure(self, spec: Dict[str, Any]) -> None:
        """Arm checkpointing / exactly-once replay from the creation spec
        (called once, before the creation closure is queued)."""
        self.ckpt_every_n = int(spec.get("checkpoint_every_n") or 0)
        self.ckpt_interval = float(spec.get("checkpoint_interval_s") or 0.0)
        self.ckpt_enabled = bool(
            flags.get("RTPU_ACTOR_CHECKPOINT")
            and (self.ckpt_every_n > 0 or self.ckpt_interval > 0.0))
        self.replay = bool(spec.get("max_task_retries"))

    # ------------------------------------------- exactly-once call replay

    @staticmethod
    def _journal_key(spec: Dict[str, Any]):
        caller = spec.get("caller")
        seq = spec.get("seqno")
        if caller is None or seq is None:
            return None
        return (caller, seq)

    def _journal_lookup(self, key) -> Optional[Dict[str, Any]]:
        with self._seq_lock:
            entries = self.journal.get(key[0])
            return entries.get(key[1]) if entries else None

    def _intercept_replay(self, spec: Dict[str, Any]) -> bool:
        """Dedup a retried call BEFORE it enters the mailbox: an already-
        applied (caller, seqno) short-circuits to its journaled result; one
        still in flight parks as a dup-waiter completed alongside the
        original. Returns True when the spec was consumed here."""
        key = self._journal_key(spec)
        if key is None:
            return False
        with self._seq_lock:
            entries = self.journal.get(key[0])
            hit = entries.get(key[1]) if entries else None
            if hit is None:
                if key in self._inflight_keys:
                    self._dup_waiters.setdefault(key, []).append(spec)
                    return True
                self._inflight_keys.add(key)
                return False
        self.runtime._complete_replayed(spec, hit)
        return True

    def note_result(self, spec: Dict[str, Any],
                    payload: Dict[str, Any]) -> None:
        """Record one applied call's result (journal + dup waiters) and
        advance the checkpoint cadence. Runs on whichever thread completed
        the call; checkpointing itself is enqueued onto the mailbox."""
        key = self._journal_key(spec)
        waiters: List[Dict[str, Any]] = []
        if key is not None and self.replay:
            with self._seq_lock:
                entries = self.journal.setdefault(key[0], {})
                entries[key[1]] = payload
                if len(entries) > self._JOURNAL_MAX:
                    for s in sorted(entries)[:len(entries)
                                             - self._JOURNAL_MAX]:
                        entries.pop(s, None)
                self._inflight_keys.discard(key)
                waiters = self._dup_waiters.pop(key, [])
        for w in waiters:
            self.runtime._complete_replayed(w, payload)
        if self.ckpt_enabled:
            self.calls_since_ckpt += 1
            if self.ckpt_every_n \
                    and self.calls_since_ckpt >= self.ckpt_every_n:
                self.request_checkpoint()

    # ------------------------------------------------ durable checkpoints

    def ckpt_due(self) -> bool:
        return (self.ckpt_enabled and self.ckpt_interval > 0.0
                and self.instance is not None and not self.exited
                and not self._ckpt_pending
                and time.monotonic() - self.last_ckpt >= self.ckpt_interval)

    def request_checkpoint(self) -> None:
        """Enqueue a checkpoint on the mailbox (strictly after every call
        queued before it, so the record reflects results callers saw)."""
        if self._ckpt_pending or self.exited:
            return
        self._ckpt_pending = True
        self.q.put({"__create__": self.do_checkpoint})

    def do_checkpoint(self) -> Optional[bytes]:
        """Serialize instance + journal under the next epoch, write the
        host-local file, ship an async copy to the controller. Mailbox
        thread only (actor state is thread-affine). Best-effort: an
        unpicklable actor keeps running with checkpointing broken, exactly
        like the drain-snapshot fallback."""
        from . import checkpoint

        self._ckpt_pending = False
        if self.exited or self.instance is None:
            return None
        with self._seq_lock:
            journal = {c: dict(e) for c, e in self.journal.items()}
        try:
            blob = checkpoint.encode(self.instance, journal,
                                     self.ckpt_epoch + 1)
        except Exception:
            return None
        self.ckpt_epoch += 1
        self.calls_since_ckpt = 0
        self.last_ckpt = time.monotonic()
        try:
            checkpoint.write_local(self.actor_id, self.ckpt_epoch, blob)
        except OSError:
            pass
        try:
            self.runtime.client.send_nowait(
                {"kind": "actor_checkpoint", "actor_id": self.actor_id,
                 "epoch": self.ckpt_epoch, "blob": blob})
        except Exception:
            pass
        return blob

    def submit(self, spec: Dict[str, Any]) -> None:
        """Enqueue in per-caller SUBMISSION order (reference:
        direct_actor_task_submitter sequence_no). Calls from one caller can
        arrive over two paths (direct socket, controller fallback) and
        overtake; out-of-order arrivals wait in a per-caller hold-back
        buffer until the gap fills — or until a bounded timeout flushes
        them, so a call lost to a path failure stalls ordering, not the
        actor."""
        if "__recv_ts__" not in spec and task_events.enabled():
            # Arrival stamp for the queue-wait phase: covers time spent in
            # the hold-back buffer AND the mailbox queue.
            spec["__recv_ts__"] = time.time()
        if self.replay and self._intercept_replay(spec):
            return  # duplicate of an applied/in-flight call: deduped
        if spec.get("task_id"):
            self.runtime.queued_actor_tasks[spec["task_id"]] = spec
        caller = spec.get("caller")
        seq = spec.get("seqno")
        if caller is None or seq is None:
            self.q.put(spec)
            return
        with self._seq_lock:
            state = self._seq.get(caller)
            if state is None:
                # Fresh caller: sequences start at 0. (A RESTARTED actor
                # joining a caller's stream mid-sequence parks the first
                # arrival in the hold-back buffer until the gap timer
                # flushes it — a one-time bounded hiccup, never a stall.)
                state = self._seq[caller] = {"next": 0, "held": {}}
            if seq < state["next"]:
                self.q.put(spec)  # late duplicate/retry: run, don't stall
                return
            if seq > state["next"]:
                state["held"][seq] = spec
                threading.Timer(self._SEQ_GAP_TIMEOUT_S,
                                self._flush_seq_gap,
                                args=(caller, seq)).start()
                return
            self.q.put(spec)
            state["next"] = seq + 1
            while state["next"] in state["held"]:
                self.q.put(state["held"].pop(state["next"]))
                state["next"] += 1

    def _flush_seq_gap(self, caller: str, seq: int) -> None:
        """Timeout fallback: the call before `seq` never arrived — release
        everything held, in order, and advance the cursor past it."""
        with self._seq_lock:
            state = self._seq.get(caller)
            if state is None or seq not in state["held"]:
                return  # gap filled in time
            for s in sorted(state["held"]):
                if s > seq:
                    break
                self.q.put(state["held"].pop(s))
            state["next"] = max(state["next"], seq + 1)
            while state["next"] in state["held"]:
                self.q.put(state["held"].pop(state["next"]))
                state["next"] += 1

    def stop(self) -> None:
        for _ in self.threads:
            self.q.put(None)
        if self.aio_loop is not None:
            self.aio_loop.call_soon_threadsafe(self.aio_loop.stop)

    def ensure_aio_loop(self):
        """Start the persistent event loop (first async method / creation).
        Locked: with a multi-threaded mailbox, two first-async-calls racing
        here could otherwise each build a loop and strand one's coroutines
        on a loop no thread runs."""
        with self._aio_lock:
            if self.aio_loop is None:
                import asyncio

                loop = asyncio.new_event_loop()
                # Async actors interleave up to max_concurrency coroutines; a
                # plain actor that happens to have one async method still gets
                # real concurrency (ray default for async actors is high).
                n = self.max_concurrency if self.max_concurrency > 1 else 100
                self.aio_sem = asyncio.Semaphore(n)
                self.aio_loop = loop
                t = threading.Thread(
                    target=self._run_aio, name=f"actor-aio-{self.actor_id[:8]}",
                    daemon=True,
                )
                t.start()
            return self.aio_loop

    def _run_aio(self) -> None:
        import asyncio

        # The loop thread belongs to exactly one actor: current_actor_id()
        # (and therefore exit_actor) must work from coroutine methods too.
        ctx.task_local.actor_id = self.actor_id
        asyncio.set_event_loop(self.aio_loop)
        self.aio_loop.run_forever()

    def _loop(self) -> None:
        while True:
            spec = self.q.get()
            if spec is None:
                return
            if "__create__" in spec:
                spec["__create__"]()
                continue
            if self.exited:
                # exit_actor already ran: a queued call must FAIL, not
                # execute on (or double-complete against) a retired actor.
                # The claim pop keeps a racing cancel from also completing.
                tid = spec.get("task_id")
                if not tid or self.runtime.queued_actor_tasks.pop(
                        tid, None) is not None:
                    self.runtime._refuse_exited(spec)
                continue
            self.runtime.run_task(spec, actor_instance=self.instance, mailbox=self)


class _NullSpan:
    """No-op stand-in for tracing.task_span when the spec carries no trace
    context — the per-task fast path pays an attribute check, not a scope."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, et, ev, tb):
        return False

    def detach_context(self) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _BatchReply:
    """Aggregation state for one pushed batch: entries contribute their
    result locations as they finish; the single correlated response
    resolves when the last one lands."""

    __slots__ = ("loop", "fut", "remaining", "locations", "error_locations",
                 "lock")

    def __init__(self, loop, fut, n: int):
        self.loop = loop
        self.fut = fut
        self.remaining = n
        self.locations: List[ObjectLocation] = []
        self.error_locations: List[ObjectLocation] = []
        self.lock = threading.Lock()

    def contribute(self, payload: Dict[str, Any]) -> None:
        with self.lock:
            self.locations.extend(payload.get("locations") or ())
            self.error_locations.extend(payload.get("error_locations") or ())
            self.remaining -= 1
            if self.remaining > 0:
                return
            result = {"locations": self.locations,
                      "error_locations": self.error_locations}

        def _set():
            if not self.fut.done():
                self.fut.set_result(result)

        self.loop.call_soon_threadsafe(_set)


class WorkerRuntime:
    def __init__(self, controller_addr: str, node_id: str):
        host, port = controller_addr.rsplit(":", 1)
        self.worker_id = WorkerID.generate()
        self.node_id = node_id
        from . import ownership as _ownership

        _ownership.set_process_label(f"worker:{self.worker_id[:8]}")
        self.client = CoreClient(host, int(port), handler=self._handle,
                                 reconnect=True,
                                 on_reconnect=self._on_reconnect)
        self.pool = ThreadPoolExecutor(max_workers=32, thread_name_prefix="task")
        # Completion batcher: task_done payloads (acks + result-location
        # publishes) buffered here coalesce into one task_done_batch frame
        # per io-loop beat instead of one loop wakeup + pickle per task.
        self._done_buf: List[Dict[str, Any]] = []
        self._done_lock = threading.Lock()
        self._done_scheduled = False
        self.functions: Dict[str, Any] = {}
        self.actors: Dict[str, ActorMailbox] = {}
        # Installed compiled-DAG plans (dag_id -> dag.resident.WorkerDAG):
        # resident loops + producer rings + stream inboxes on this worker.
        self.dag_channels: Dict[str, Any] = {}
        self.running_threads: Dict[str, int] = {}  # task_id -> thread ident
        self.cancelled_tasks: set = set()  # ray.cancel'd before/while running
        # Actor calls sitting in a mailbox (or its hold-back buffer), not
        # yet executing: task_id -> spec. A cancel that atomically pops an
        # entry owns its completion and fails it IMMEDIATELY — no waiting
        # behind whatever runs ahead of it; run_task's matching pop claims
        # execution, and a miss there means a cancel won the race.
        self.queued_actor_tasks: Dict[str, Dict[str, Any]] = {}
        self.shutdown_event = threading.Event()
        # Direct-dispatch server: peers push actor tasks here without a
        # controller hop (reference: direct task transport,
        # src/ray/core_worker/transport/direct_task_transport.h:222 — the
        # lease-then-push design keeping the control plane off the data
        # path). Advertised to the controller in the register message.
        self.direct_port = self._start_direct_server()
        # Context must be live before registration: the controller may push a
        # task the instant the register request lands.
        ctx.set_worker_context(ctx.WorkerContext(client=self.client, node_id=node_id, role="worker"))
        # Apply the runtime env BEFORE registering: the controller may push
        # a task the moment registration lands, and the env (cwd, sys.path,
        # env_vars) must already be in place (the pip venv part was applied
        # by the spawner — this interpreter is the venv's).
        env_hash = ""
        renv_json = flags.get("RTPU_RUNTIME_ENV")
        if renv_json:
            import json as _json

            from . import runtime_env as renv

            norm = _json.loads(renv_json)
            renv.apply_in_worker(norm, self.client)
            env_hash = norm.get("hash", "")
        # Tee stdout/stderr to the driver console via the controller
        # (reference: _private/log_monitor.py tailing worker logs; here the
        # worker pushes its own lines — no per-node tail daemon needed).
        # Installed BEFORE registering: the controller may push a task the
        # instant registration lands, and a print() from that first task
        # must not race the tee install (it would go only to the log file,
        # never to the driver). The same tee stamps task/actor attribution
        # markers + byte-range index entries into the spawn's log file
        # (worker_logs.LogAttributor) so one task's output is remotely
        # retrievable without scanning.
        from . import worker_logs

        self._log_attributor = (
            worker_logs.LogAttributor.create(self.worker_id, node_id)
            if flags.get("RTPU_LOG_ATTRIBUTION") else None)
        if flags.get("RTPU_LOG_TO_DRIVER") \
                or self._log_attributor is not None:
            self._install_log_forwarder()
        self._env_hash = env_hash
        self.client.request(self._register_msg())

        # Controller-connection watch: a dropped connection first enters
        # the client's capped-backoff reconnect loop (the controller may
        # just be bouncing — reference: NotifyGCSRestart re-registration,
        # core_worker.proto:392). Only when the reconnect deadline passes
        # does the worker fate-share and die (an orphaned worker would
        # leak forever).
        async def _watch_conn() -> None:
            import asyncio

            while not self.shutdown_event.is_set():
                conn = self.client.conn
                await conn.closed.wait()
                if self.shutdown_event.is_set():
                    return
                ok = await asyncio.get_running_loop().run_in_executor(
                    None, self._try_reconnect)
                if not ok:
                    self.shutdown_event.set()
                    return

        self.client.io.call_nowait(_watch_conn())

    # ------------------------------------------------- controller reconnect

    def _register_msg(self, reconnect: bool = False) -> Dict[str, Any]:
        msg = {
            "kind": "register",
            "role": "worker",
            "worker_id": self.worker_id,
            "node_id": self.node_id,
            "spawn_token": flags.get("RTPU_SPAWN_TOKEN"),
            "tpu_capable": flags.get("RTPU_TPU_WORKER"),
            # Spawner-assigned chip visibility (agent- or controller-
            # side): reported so the scheduler can match workers to
            # tasks by chip count, not just TPU-capability.
            "chip_ids": [int(x) for x in
                         (flags.get("TPU_VISIBLE_CHIPS") or "").split(",")
                         if x != ""],
            "env_hash": self._env_hash,
            "direct_port": self.direct_port,
            "pid": os.getpid(),
        }
        if reconnect:
            msg["reconnect"] = True
            # Tasks currently executing on this worker: a restarted
            # controller re-claims them so (a) a resubmitted duplicate
            # isn't also scheduled and (b) a node drain's quiesce check
            # keeps waiting for work it would otherwise not see.
            msg["running"] = list(self.running_threads.keys())
            # Re-claim hosted actors: a restarted controller rebuilds its
            # actor directory from these reports, keeping live instances
            # (and their state) over queued re-creations.
            msg["actors"] = [
                self._actor_claim(aid, mb)
                for aid, mb in list(self.actors.items())
                if not mb.exited and mb.instance is not None
            ]
        return msg

    @staticmethod
    def _actor_claim(actor_id: str, mb: "ActorMailbox") -> Dict[str, Any]:
        spec = getattr(mb, "spec", None) or {}
        return {
            "actor_id": actor_id,
            "name": spec.get("name"),
            "namespace": spec.get("namespace", "default"),
            "detached": bool(spec.get("detached")),
            "max_restarts": int(spec.get("max_restarts", 0)),
            "resources": dict(spec.get("resources") or {}),
        }

    def _try_reconnect(self) -> bool:
        try:
            self.client.ensure_connected()
            return True
        except Exception as e:
            import sys as _sys

            print(f"[worker] controller reconnect failed: {e!r}; "
                  f"fate-sharing\n{traceback.format_exc()}",
                  file=_sys.stderr, flush=True)
            return False

    def _on_reconnect(self, client: CoreClient) -> None:
        """Runs on the fresh connection before any retried request:
        re-register under the existing worker id, re-report chips and
        hosted actors, drop actors the controller says were re-created
        elsewhere while we were away."""
        deadline = time.monotonic() + flags.get("RTPU_RECONNECT_MAX_S")
        # Bounded handshake under the partition-hardening RPC timeout: a
        # register into a still-blackholed network fails fast and retries
        # from the client's dial loop instead of camping 30s per attempt.
        rpc_t = float(flags.get("RTPU_RPC_TIMEOUT_S") or 0.0)
        while True:
            reply = client.io.call(
                client.conn.request(self._register_msg(reconnect=True),
                                    timeout=rpc_t * 2 if rpc_t else None),
                timeout=(rpc_t * 2 if rpc_t else 30) + 5)
            if reply and reply.get("ok"):
                break
            if not (reply and reply.get("retry")) \
                    or time.monotonic() >= deadline:
                raise ConnectionError(
                    "controller refused worker re-registration")
            # Our node (host agent) has not re-registered yet: give it a
            # beat and try again.
            time.sleep(0.3)
        for aid in reply.get("drop_actors") or ():
            mb = self.actors.pop(aid, None)
            if mb is not None:
                mb.exited = True
                mb.stop()

    def _install_log_forwarder(self) -> None:
        import sys

        runtime = self

        class _Tee:
            # Forwarded lines cap at 8KB: \r-only writers (progress bars)
            # must not grow the buffer without bound, and a never-ending
            # line is forwarded in chunks rather than buffered forever.
            _MAX_BUF = 8192

            def __init__(self, inner, stream: str):
                self._inner = inner
                self._stream = stream
                self._buf = ""
                self._lock = threading.Lock()

            def _emit(self, line: str) -> None:
                if not line.strip():
                    return
                if not flags.get("RTPU_LOG_TO_DRIVER"):
                    return
                try:
                    runtime.client.send_nowait({
                        "kind": "worker_log", "line": line,
                        "pid": os.getpid(),
                        "worker_id": runtime.worker_id,
                        "stream": self._stream,
                    })
                except Exception:
                    pass

            def write(self, text: str) -> int:
                attr = runtime._log_attributor
                if attr is not None and flags.get("RTPU_LOG_ATTRIBUTION"):
                    # Attribution path: marker stamping + byte-range index
                    # entries keyed by the WRITING thread's execution
                    # context (the task pool / mailbox threads set it).
                    n = attr.write(self._inner, text, self._stream,
                                   ctx.current_task_id(),
                                   ctx.current_actor_id(),
                                   getattr(ctx.task_local, "label", None))
                else:
                    n = self._inner.write(text)
                # The 32-thread task pool writes concurrently; _buf updates
                # must be atomic or lines interleave/vanish.
                with self._lock:
                    self._buf += text
                    self._buf = self._buf.replace("\r\n", "\n")
                    lines = self._buf.replace("\r", "\n").split("\n")
                    self._buf = lines.pop()
                    if len(self._buf) > self._MAX_BUF:
                        lines.append(self._buf)
                        self._buf = ""
                for line in lines:
                    self._emit(line)
                return n

            def flush(self) -> None:
                self._inner.flush()
                # An explicit flush is a visibility request: publish the
                # pending attribution range too, so a live `rtpu logs`
                # follower sees the line now, not at the next context
                # switch or batching threshold.
                attr = runtime._log_attributor
                if attr is not None:
                    attr.flush()

            def __getattr__(self, name):
                return getattr(self._inner, name)

        sys.stdout = _Tee(sys.stdout, "stdout")
        sys.stderr = _Tee(sys.stderr, "stderr")

    # ------------------------------------------------------- direct dispatch

    def _start_direct_server(self) -> int:
        from . import protocol

        # Bind the interface this worker uses to reach the controller —
        # exactly the address the controller advertises to peers (it reads
        # our connection's peername, controller._h_lease_worker). A loopback
        # cluster therefore stays loopback; binding 0.0.0.0 would expose an
        # unauthenticated execute-pickled-callable endpoint on every
        # interface of the host (advisor r4). RTPU_DIRECT_BIND overrides
        # for multi-homed hosts where peers ride a different interface.
        bind_host = flags.get("RTPU_DIRECT_BIND")
        if not bind_host:
            try:
                bind_host = self.client.conn.writer.get_extra_info(
                    "sockname")[0]
            except Exception:
                bind_host = "127.0.0.1"

        async def serve():
            async def on_conn(reader, writer):
                conn = protocol.Connection(
                    reader, writer, handler=self._handle_direct,
                    name="direct")
                conn.start()

            return await __import__("asyncio").start_server(
                on_conn, bind_host, 0)

        self._direct_server = self.client.io.call(serve(), timeout=10)
        port = self._direct_server.sockets[0].getsockname()[1]
        # The direct server doubles as this worker's ownership ref channel
        # (borrow/hold messages land in _handle_direct's ref_* branch).
        from . import ownership

        ownership.set_self_addr(bind_host, port)
        return port

    async def _handle_direct(self, conn, msg):
        """Peer-pushed actor task: enqueue on the mailbox, answer with the
        result locations when it completes. The response rides the same
        connection (request/response correlation), so the caller gets the
        locations with zero controller involvement.

        The *_batch kinds carry many specs in one framed message (one
        unpickle per wave-slice instead of per call); the single response
        aggregates every entry's result locations and resolves when the
        last entry finishes — per-entry results stream to the controller
        via the completion batcher in the meantime, so a mid-batch worker
        death leaves the caller able to distinguish completed entries
        (locations published) from never-ran ones."""
        import asyncio

        kind = msg["kind"]
        if kind.startswith("ref_"):
            from . import ownership

            return ownership.handle_ref_message(msg)
        if kind.startswith("pull_"):
            # Producer-served object plane: this worker serves its own
            # objects' bytes over the direct server (Ray's plasma/pull-
            # manager split — the controller keeps location metadata only;
            # consumers fall back to the host agent when this worker dies).
            from . import transfer

            return await transfer.handle_pull_server_message(conn, msg)
        if kind.startswith("dag_"):
            # Compiled-DAG channel plane: install/teardown/status ride the
            # driver's per-DAG connection; dag_channel_item frames are the
            # cross-host channel legs (raw-tail pushes, no response).
            from ray_tpu.dag import resident

            return resident.handle_direct_message(self, conn, msg)
        if kind == "cancel_task":
            self._cancel_task(msg["task_id"])
            return None
        loop = asyncio.get_running_loop()
        if kind in ("direct_task_batch", "direct_actor_task_batch"):
            specs = msg["specs"]
            fut = loop.create_future()
            state = _BatchReply(loop, fut, len(specs))
            if kind == "direct_actor_task_batch":
                mb = self.actors.get(specs[0]["actor_id"]) if specs else None
                if mb is None:
                    # Typed refusal BEFORE any entry runs: the whole batch
                    # provably never executed, so the caller resubmits it
                    # through the controller.
                    raise ActorNotHostedError(
                        f"actor {(specs[0]['actor_id'][:8]) if specs else '?'}"
                        f" is not hosted on this worker")
            now = time.time() if task_events.enabled() else None
            for spec in specs:
                if now is not None:
                    spec["__recv_ts__"] = now
                spec["__batch__"] = state
                if kind == "direct_task_batch":
                    spec["__leased__"] = True
                    self._lease_submit(spec)
                else:
                    mb.submit(spec)
            return await fut
        spec = msg["spec"]
        if task_events.enabled():
            spec["__recv_ts__"] = time.time()
        if spec.get("streaming"):
            # Generator state lives in the controller; a direct streaming
            # call would hang the caller's future forever.
            raise ValueError("streaming calls must go through the controller")
        # The executing thread POPS "__direct__" when it finishes — bind the
        # future to a local BEFORE handing the spec over, or a fast task
        # completes (and pops) before this coroutine evaluates the
        # subscript and the await raises KeyError.
        fut = loop.create_future()
        if kind == "direct_task":
            # Leased stateless task (reference direct_task_transport.h:222):
            # executes SERIALLY — the lease reserves one CPU, so pushed
            # tasks queue here instead of fanning out over the pool.
            spec["__direct__"] = (fut, loop)
            spec["__leased__"] = True
            self._lease_submit(spec)
            return await fut
        if kind != "direct_actor_task":
            raise ValueError(f"direct server: unknown kind {kind!r}")
        mb = self.actors.get(spec["actor_id"])
        if mb is None:
            # Typed refusal BEFORE any user code runs: the caller knows the
            # call never executed and resubmits through the controller
            # (which has the actor's post-migration address).
            raise ActorNotHostedError(
                f"actor {spec['actor_id'][:8]} is not hosted on this worker "
                f"(died or restarted elsewhere)")
        spec["__direct__"] = (fut, loop)
        mb.submit(spec)
        return await fut

    def _lease_submit(self, spec: Dict[str, Any]) -> None:
        """Queue a leased task for SERIAL execution. A dedicated thread +
        SimpleQueue instead of a ThreadPoolExecutor: submit() there takes
        locks and allocates an unused Future per task — measurable at
        direct-dispatch rates."""
        q = getattr(self, "_lease_q", None)
        if q is None:
            q = self._lease_q = queue.SimpleQueue()

            def _run() -> None:
                while True:
                    s = q.get()
                    self.run_task(s)

            threading.Thread(target=_run, name="lease",
                             daemon=True).start()
        q.put(spec)

    def _finish_direct(self, spec: Dict[str, Any], payload: Dict[str, Any]) -> bool:
        """Resolve a direct caller's future; returns True if this spec came
        through the direct server (single push or batch entry)."""
        st = spec.pop("__batch__", None)
        if st is not None:
            st.contribute(payload)
            return True
        df = spec.pop("__direct__", None)
        if df is None:
            return False
        fut, loop = df

        def _set():
            if not fut.done():
                fut.set_result(payload)

        loop.call_soon_threadsafe(_set)
        return True

    # ----------------------------------------------------------- push handler

    def _refuse_exited(self, spec: Dict[str, Any]) -> None:
        """A call queued behind exit_actor: direct pushes get their reply
        failed; controller-path specs are dropped (the controller already
        stored ActorDiedError for them when it retired the actor —
        completing them here would double-write the return objects)."""
        if "__direct__" in spec:
            self._complete_error(spec, ActorDiedError(
                "actor exited via exit_actor() before this call ran"), "")

    def _handle_actor_exit(self, spec: Dict[str, Any]) -> None:
        """Intentional exit (exit_actor): the triggering call succeeds
        with None (shaped to its num_returns), the controller retires the
        actor WITHOUT restart, the mailbox refuses everything queued."""
        aid = spec.get("actor_id")
        mb = self.actors.get(aid) if aid else None
        if mb is not None:
            mb.exited = True  # BEFORE completing: no queued call may run
        if aid:
            from . import events

            events.emit(
                "INFO", "ACTOR_EXIT",
                f"actor {aid[:8]} exited intentionally via exit_actor()",
                actor_id=aid, worker_id=self.worker_id,
                node_id=self.node_id)
        n = len(spec.get("return_ids") or ())
        self._complete_ok(spec, None if n <= 1 else [None] * n)
        if not aid:
            return
        ok = False
        for _ in range(3):
            try:
                self.client.request({"kind": "actor_exit", "actor_id": aid})
                ok = True
                break
            except Exception:
                time.sleep(0.5)
        if not ok:
            # The control connection is almost certainly gone — fate-share
            # (the watch task would kill us anyway); dying via the normal
            # worker-death path at least fails the actor visibly instead
            # of leaving the controller believing it is alive.
            import sys as _sys

            print("[worker] actor_exit unreachable; fate-sharing",
                  file=_sys.stderr, flush=True)
            self.shutdown_event.set()
        self.actors.pop(aid, None)
        if mb is not None:
            mb.stop()
        from . import checkpoint as _ckpt

        _ckpt.prune_local(aid)  # retired for good: no record may resurrect it

    def _cancel_task(self, task_id: str) -> None:
        """Non-force ray.cancel (reference: TaskCancelledError raised in
        the executing thread via the CPython async-exception hook). A task
        still QUEUED here (lease executor / actor mailbox) is marked and
        refused at run_task start; a RUNNING one sees the exception at its
        next bytecode boundary."""
        queued = self.queued_actor_tasks.pop(task_id, None)
        if queued is not None:
            # Still in an actor mailbox: this pop claims the call — fail
            # it NOW, without waiting behind whatever executes ahead of it
            # (the mailbox dequeue sees the missing claim and skips).
            from .controller import TaskCancelledError

            self._complete_error(queued, TaskCancelledError(
                f"actor call {task_id[:8]} was cancelled while queued"), "")
            return
        if len(self.cancelled_tasks) > 8192:
            # Recursive-cancel broadcasts mark every worker; ids for tasks
            # that never arrive here would otherwise accumulate forever.
            self.cancelled_tasks.pop()
        self.cancelled_tasks.add(task_id)
        ident = self.running_threads.get(task_id)
        if ident is not None:
            import ctypes as _ct

            from .controller import TaskCancelledError

            _ct.pythonapi.PyThreadState_SetAsyncExc(
                _ct.c_ulong(ident), _ct.py_object(TaskCancelledError))

    def _admit(self, spec: Dict[str, Any]) -> bool:
        """Local admission (reference raylet spillback): a host at the edge
        of memory exhaustion rejects the dispatch back to the scheduler
        instead of starting work it will likely be OOM-killed for. Capped
        per task so a cluster-wide pressure wave can't ping-pong a spec
        forever — after two spills it runs wherever it lands."""
        frac_limit = flags.get("RTPU_SPILLBACK_MEM_FRACTION")
        if not frac_limit or spec.get("spillback_count", 0) >= 2:
            return True
        try:
            import psutil

            if psutil.virtual_memory().percent / 100.0 >= frac_limit:
                return False
        except Exception:
            pass
        return True

    async def _handle(self, conn, msg):
        kind = msg["kind"]
        if kind == "execute_task":
            spec = msg["spec"]
            if task_events.enabled():
                spec["__recv_ts__"] = time.time()
            if not self._admit(spec):
                from . import events

                events.emit(
                    "WARNING", "TASK_SPILLBACK",
                    f"worker {self.worker_id[:8]} rejected task "
                    f"{spec.get('label') or spec['task_id'][:8]} under "
                    f"host memory pressure",
                    task_id=spec["task_id"], worker_id=self.worker_id,
                    node_id=self.node_id)
                await conn.send({"kind": "task_spillback",
                                 "task_id": spec["task_id"],
                                 "worker_id": self.worker_id})
                return None
            self.pool.submit(self.run_task, spec)
        elif kind == "instantiate_actor":
            self._instantiate_actor(msg["spec"])
        elif kind == "execute_actor_task":
            spec = msg["spec"]
            mb = self.actors.get(spec["actor_id"])
            if mb is not None:
                mb.submit(spec)
            else:
                # The actor left this worker (killed, or migrated off a
                # draining node) while the dispatch was in flight. The call
                # never ran, so bounce it back to the controller — which
                # routes to the actor's new host, buffers while it
                # re-creates, or stores ActorDiedError if it is truly dead.
                # Bounded so a stale directory can't ping-pong forever; a
                # silent drop would hang the caller.
                spec = dict(spec, __rehost__=spec.get("__rehost__", 0) + 1)

                def _bounce(spec=spec):
                    try:
                        self.client.request(
                            {"kind": "submit_actor_task", "spec": spec})
                    except Exception:
                        self._complete_error(spec, ActorNotHostedError(
                            f"actor {spec['actor_id'][:8]} is no longer "
                            f"hosted on this worker"), "")

                if spec["__rehost__"] <= 3:
                    self.pool.submit(_bounce)
                else:
                    self.pool.submit(
                        self._complete_error, spec,
                        ActorDiedError(
                            f"actor {spec['actor_id'][:8]} is no longer "
                            f"hosted on this worker"), "")
        elif kind == "snapshot_actor":
            # Drain migration: serialize the actor instance ON ITS MAILBOX
            # THREAD (state is thread-affine), after every already-queued
            # call — so the snapshot reflects all calls the caller saw
            # complete. Best-effort: unpicklable/slow actors fall back to a
            # fresh constructor run on the new node.
            return await self._snapshot_actor(msg["actor_id"])
        elif kind == "checkpoint_actor":
            # On-demand durable checkpoint (the memory monitor's final
            # checkpoint before an OOM kill, and tests): the response
            # carries the record so the controller stores it synchronously
            # before the SIGKILL lands.
            return await self._checkpoint_actor(msg["actor_id"])
        elif kind == "drop_actor":
            # The controller moved this actor elsewhere: retire the local
            # instance so post-snapshot mutations cannot be silently lost —
            # and prune this host's checkpoint files, which are stale the
            # moment the actor lives (and checkpoints) somewhere else.
            mb = self.actors.pop(msg["actor_id"], None)
            if mb is not None:
                mb.exited = True
                mb.stop()
            from . import checkpoint as _ckpt

            _ckpt.prune_local(msg["actor_id"])
        elif kind == "cancel_task":
            self._cancel_task(msg["task_id"])
        elif kind == "shutdown":
            self.shutdown_event.set()
        elif kind == "ref_dump":
            # Ownership introspection for `rtpu memory` (reference: the
            # reference-table rows `ray memory` collects per worker); same
            # off-loop reply pattern as stack_dump.
            from . import ownership

            st = ownership.stats()
            threading.Thread(
                target=lambda: self.client.request(
                    {"kind": "profile_result", "req_id": msg["req_id"],
                     "worker_id": self.worker_id, "text": st}),
                daemon=True).start()
        elif kind == "census_dump":
            # Object-census shard for the object_census fan-out: full
            # per-ref rows (owner/size/tier/pins/callsite) vs ref_dump's
            # summary counters; same off-loop reply pattern.
            from . import ownership

            def _census_reply(req_id=msg["req_id"]):
                try:
                    shard = ownership.census_shard()
                except Exception as e:
                    shard = {"error": repr(e), "rows": []}
                try:
                    self.client.request(
                        {"kind": "profile_result", "req_id": req_id,
                         "worker_id": self.worker_id, "text": shard})
                except Exception:
                    pass

            threading.Thread(target=_census_reply, daemon=True).start()
        elif kind == "dag_spans":
            # Channel-meter span ring for `state.dag_timeline()` (the
            # dag_timeline fan-out): recent per-stage step spans with
            # recv/compute/send/blocked phase ns; same off-loop reply
            # pattern as stack_dump.
            def _spans_reply(req_id=msg["req_id"], dag=msg.get("dag")):
                import json as _json

                from ray_tpu.dag import meter as _meter

                try:
                    text = _json.dumps(_meter.spans_snapshot(self, dag))
                except Exception as e:
                    text = _json.dumps({"error": repr(e)})
                try:
                    self.client.request(
                        {"kind": "profile_result", "req_id": req_id,
                         "worker_id": self.worker_id, "text": text})
                except Exception:
                    pass

            threading.Thread(target=_spans_reply, daemon=True).start()
        elif kind == "stack_dump":
            # On-demand profiling (reference: reporter agent py-spy dump):
            # format every thread's current stack and reply off the event
            # loop (client.request blocks).
            text = self._format_stacks()
            threading.Thread(
                target=lambda: self.client.request(
                    {"kind": "profile_result", "req_id": msg["req_id"],
                     "worker_id": self.worker_id, "text": text}),
                daemon=True).start()
        elif kind == "profile":
            # Wall-clock sampling profiler (core/profiler.py): sample this
            # process's threads for the requested duration on a daemon
            # thread (the sampler sleeps between ticks — it must not sit
            # on the event loop), then reply via the stack_dump path.
            def _run_profile(duration=float(msg.get("duration", 2.0)),
                             hz=float(msg.get("hz", 67.0)),
                             req_id=msg["req_id"]):
                from . import profiler

                try:
                    if not flags.get("RTPU_PROFILER"):
                        import json as _json

                        text = _json.dumps(
                            {"error": "profiler disabled on worker "
                                      "(RTPU_PROFILER=0)"})
                    else:
                        text = profiler.profile_and_encode(duration, hz)
                except Exception as e:
                    import json as _json

                    text = _json.dumps({"error": repr(e)})
                try:
                    self.client.request(
                        {"kind": "profile_result", "req_id": req_id,
                         "worker_id": self.worker_id, "text": text})
                except Exception:
                    pass

            threading.Thread(target=_run_profile, daemon=True).start()
        elif kind == "pubsub":
            ctx.deliver_pubsub(msg["channel"], msg["data"])
        elif kind == "pubsub_batch":
            for item in msg["items"]:
                ctx.deliver_pubsub(item["channel"], item["data"])
        return None

    async def _snapshot_actor(self, actor_id: str) -> Dict[str, Any]:
        import asyncio

        mb = self.actors.get(actor_id)
        if mb is None or mb.exited or mb.instance is None:
            return {"error": "actor not hosted here"}
        loop = asyncio.get_running_loop()
        fut: "asyncio.Future" = loop.create_future()

        def snap():
            from . import checkpoint

            try:
                # Record format (instance + replay journal + epoch): a
                # migrated replayable actor keeps its dedup journal, and
                # the snapshot supersedes any older durable checkpoint.
                with mb._seq_lock:
                    journal = {c: dict(e) for c, e in mb.journal.items()}
                blob = checkpoint.encode(mb.instance, journal,
                                         mb.ckpt_epoch + 1)
                payload: Dict[str, Any] = {"blob": blob}
            except Exception as e:  # unpicklable state: ctor fallback
                payload = {"error": repr(e)}
            loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result(payload))

        # Rides the mailbox's closure lane (same as __init__), so it runs
        # strictly after every call queued before the migration began. A
        # compiled-DAG resident loop owns the mailbox thread and never
        # drains that lane — hand the closure to the loop instead; it runs
        # it between microbatches (a seq-consistent point) and parks.
        routed = False
        for wd in self.dag_channels.values():
            if wd.request_snapshot(actor_id, snap):
                routed = True
                break
        if not routed:
            mb.q.put({"__create__": snap})
        try:
            return await asyncio.wait_for(fut, timeout=8.0)
        except asyncio.TimeoutError:
            return {"error": "snapshot timed out behind queued calls"}

    async def _checkpoint_actor(self, actor_id: str) -> Dict[str, Any]:
        """On-demand durable checkpoint, on the mailbox thread after every
        queued call. Returns {epoch, blob} so the caller (the controller's
        OOM path) can store the record without waiting for the async ship."""
        import asyncio

        mb = self.actors.get(actor_id)
        if mb is None or mb.exited or mb.instance is None:
            return {"error": "actor not hosted here"}
        loop = asyncio.get_running_loop()
        fut: "asyncio.Future" = loop.create_future()

        def run():
            blob = mb.do_checkpoint()
            payload = ({"epoch": mb.ckpt_epoch, "blob": blob}
                       if blob is not None
                       else {"error": "checkpoint failed"})
            loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result(payload))

        mb.q.put({"__create__": run})
        try:
            return await asyncio.wait_for(fut, timeout=8.0)
        except asyncio.TimeoutError:
            return {"error": "checkpoint timed out behind queued calls"}

    def _format_stacks(self) -> str:
        import sys

        names = {t.ident: t.name for t in threading.enumerate()}
        parts = [f"pid={os.getpid()} worker={self.worker_id}"]
        for tid, frame in sys._current_frames().items():
            parts.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
            parts.append("".join(traceback.format_stack(frame)))
        return "\n".join(parts)

    # -------------------------------------------------------------- execution

    def _load_function(self, func_id: str) -> Any:
        fn = self.functions.get(func_id)
        if fn is None:
            blob = self.client.request({"kind": "fetch_function", "func_id": func_id})
            fn = cloudpickle.loads(blob)
            self.functions[func_id] = fn
        return fn

    def _resolve_args(self, spec: Dict[str, Any]) -> tuple:
        args, kwargs = pickle.loads(spec["args_blob"])
        hints: Dict[str, ObjectLocation] = spec.get("loc_hints") or {}
        ref_ids = [v.object_id for v in (*args, *kwargs.values())
                   if isinstance(v, ArgRef) and v.object_id not in hints]
        locs: Dict[str, ObjectLocation] = dict(hints)
        if ref_ids:
            # Owners before the directory (reference ownership protocol:
            # the owner is the authority for its objects; the controller
            # keeps a cache). ONE batched round-trip per distinct owner;
            # anything an owner can't resolve (or a dead owner's whole
            # group) falls through to one batched controller
            # get_locations.
            from . import ownership

            dep_owners: Dict[str, str] = spec.get("dep_owners") or {}
            by_owner: Dict[str, List[str]] = {}
            for oid in ref_ids:
                owner = dep_owners.get(oid)
                if owner:
                    by_owner.setdefault(owner, []).append(oid)
            for owner, oids in by_owner.items():
                locs.update(ownership.locate_from_owner_batch(oids, owner))
            still = [oid for oid in ref_ids if oid not in locs]
            if still:
                locs.update(self.client.request(
                    {"kind": "get_locations", "object_ids": still,
                     "node_id": self.node_id}))

        def resolve(v: Any) -> Any:
            if isinstance(v, ArgRef):
                val, loc = get_bytes_with_refresh(
                    locs[v.object_id], v.object_id, self.client.request)
                if loc.is_error:
                    raise val if isinstance(val, BaseException) else RuntimeError(val)
                return val
            return v

        args = tuple(resolve(a) for a in args)
        kwargs = {k: resolve(v) for k, v in kwargs.items()}
        return args, kwargs

    def run_task(
        self,
        spec: Dict[str, Any],
        actor_instance: Any = None,
        mailbox: Optional["ActorMailbox"] = None,
    ) -> None:
        task_id = spec["task_id"]
        if spec.get("__leased__"):
            # Directly-pushed task: the controller never saw a "running"
            # event — the completion report carries the start time so the
            # timeline can synthesize the full span.
            spec["__start_ts__"] = time.time()
        if task_events.enabled():
            # Flight recorder (TaskEventBuffer analog): phase timestamps
            # accumulate in __ph__ and are finalized by the completion
            # paths — which cover sync tasks, actor calls, async actor
            # coroutines (drive()), streaming, and every error path alike.
            now = time.time()
            ph = spec["__ph__"] = {"start_ts": now}
            recv = spec.pop("__recv_ts__", None)
            if recv is not None:
                ph["queue_wait_s"] = max(0.0, now - recv)
                sub = spec.get("submit_ts")
                if sub is not None:
                    ph["scheduling_delay_s"] = max(0.0, recv - sub)
        tls = ctx.task_local
        tls.task_id = task_id
        tls.label = spec.get("label", "")
        if spec.get("actor_id") and actor_instance is not None:
            tls.actor_id = spec["actor_id"]
        if mailbox is not None:
            if (spec.get("actor_id")
                    and self.queued_actor_tasks.pop(task_id, None) is None):
                # A cancel atomically claimed this call while it sat in
                # the mailbox and already completed it with
                # TaskCancelledError — skip without double-completing.
                tls.task_id = None
                return
            if mailbox.replay or mailbox.ckpt_enabled:
                # Completion paths journal the result / advance the
                # checkpoint cadence through this handle (popped exactly
                # once there).
                spec["__mb__"] = mailbox
        if task_id in self.cancelled_tasks:
            from .controller import TaskCancelledError

            self.cancelled_tasks.discard(task_id)
            self._complete_error(spec, TaskCancelledError(
                f"task {task_id[:8]} was cancelled before it started"), "")
            tls.task_id = None
            return
        dl = spec.get("deadline_ts")
        if dl is not None and time.time() > dl:
            # Dequeue-time deadline check (.options(deadline_s=...)): an
            # expired spec — plain-task pool or actor mailbox alike — is
            # refused, never executed.
            from .controller import DeadlineExceededError

            self._complete_error(spec, DeadlineExceededError(
                f"task {task_id[:8]} deadline passed before it started"), "")
            tls.task_id = None
            return
        self.running_threads[task_id] = threading.get_ident()
        # Borrow every dep (ordered before the hold_release on the same
        # owner connection), so the submitter's in-flight holds can retire
        # the moment this worker protects the objects itself. The handles
        # die with this frame — after arg VALUES are materialized the dep
        # bytes are no longer needed here. Guarded on dep_owners so a
        # dep-less task (the direct-dispatch common case) skips the module
        # call and its flag read entirely.
        if spec.get("dep_owners"):
            from . import ownership

            _held = ownership.acquire_spec_refs(spec)  # noqa: F841
        # Manual span scope: the consumer span must cover the ACTUAL body —
        # for async actor methods the user code runs in the awaited
        # coroutine, so span ownership transfers into drive() and closes
        # there (a `with` around the sync call would record a ~0ms success
        # for a 10s coroutine and miss its exceptions). A spec with no
        # carried trace context (tracing off at the submitter — the
        # default) gets the no-op span, skipping scope setup per task.
        if spec.get("trace_ctx"):
            from ray_tpu.util.tracing import task_span

            span = task_span(spec)
        else:
            span = _NULL_SPAN
        span.__enter__()
        span_transferred = False
        try:
            args, kwargs = self._resolve_args(spec)
            ph = spec.get("__ph__")
            if ph is not None:
                t = time.time()
                ph["arg_fetch_s"] = max(0.0, t - ph["start_ts"])
                ph["exec_start"] = t
            if spec.get("actor_id") and actor_instance is not None:
                method = getattr(actor_instance, spec["method_name"])
                result = method(*args, **kwargs)
            else:
                fn = self._load_function(spec["func_id"])
                result = fn(*args, **kwargs)
            if _is_coroutine(result):
                import asyncio

                if spec.get("streaming"):
                    raise TypeError(
                        "num_returns='streaming' requires a (sync or async) "
                        "generator; this method is a plain coroutine"
                    )
                if mailbox is not None:
                    # Async actor method: hand the coroutine to the actor's
                    # persistent loop and release the mailbox thread — the
                    # next call dispatches immediately, so awaits interleave.
                    loop = mailbox.ensure_aio_loop()
                    sem = mailbox.aio_sem
                    span_transferred = True
                    # The mailbox thread moves on to its next call: restore
                    # its current-span slot NOW; the span itself stays open
                    # until the coroutine settles in drive().
                    span.detach_context()

                    async def drive(result=result, spec=spec, span=span):
                        async with sem:
                            try:
                                value = await result
                            except ActorExitSignal:
                                span.__exit__(None, None, None)
                                await asyncio.get_running_loop().run_in_executor(
                                    None,
                                    lambda: self._handle_actor_exit(spec))
                                return
                            except BaseException as e:  # noqa: BLE001
                                tb = traceback.format_exc()
                                span.__exit__(type(e), e, e.__traceback__)
                                await asyncio.get_running_loop().run_in_executor(
                                    None,
                                    lambda: self._complete_error(spec, e, tb),
                                )
                            else:
                                span.__exit__(None, None, None)
                                # Serialization + the controller round-trip
                                # block; keep them off the actor loop so
                                # other in-flight awaits keep interleaving.
                                await asyncio.get_running_loop().run_in_executor(
                                    None, lambda: self._complete_ok(spec, value)
                                )

                    asyncio.run_coroutine_threadsafe(drive(), loop)
                    return
                result = asyncio.run(result)
            if _is_async_gen(result):
                if not spec.get("streaming"):
                    raise TypeError(
                        "async generator methods require "
                        "num_returns='streaming'"
                    )
                if mailbox is not None:
                    self._run_streaming_async(spec, result, mailbox)
                    return
                raise TypeError(
                    "async generators are only supported on actors"
                )
            if spec.get("streaming"):
                self._run_streaming(spec, result)
                return
            self._complete_ok(spec, result)
        except ActorExitSignal:
            self._handle_actor_exit(spec)
        except BaseException as e:  # noqa: BLE001 — every task error is captured
            self._complete_error(spec, e, traceback.format_exc())
        finally:
            if not span_transferred:
                import sys as _sys

                span.__exit__(*_sys.exc_info())
            self.running_threads.pop(task_id, None)
            tls.task_id = None
            if self._log_attributor is not None:
                # Close out the task's pending byte range so its indexed
                # output is complete once the result is observable.
                self._log_attributor.flush()

    def _ship_done(self, msg: Dict[str, Any]) -> None:
        """Fire-and-forget a task_done to the controller, coalesced: every
        payload buffered during one io-loop beat ships as a single framed
        task_done_batch (one wakeup, one pickle, one syscall). Best-effort
        exactly like the per-task send it replaces — a batch in flight when
        the controller bounces is covered by the driver's resubmission and
        the direct caller's recovery probe, not by redelivery here."""
        if not flags.get("RTPU_SUBMIT_BATCH"):
            self.client.send_nowait(msg)
            return
        flush_now = False
        with self._done_lock:
            self._done_buf.append(msg)
            if len(self._done_buf) >= flags.get("RTPU_SUBMIT_BATCH_MAX"):
                flush_now = True
            elif self._done_scheduled:
                return
            self._done_scheduled = True
        try:
            if flush_now:
                self._flush_done_threadsafe()
            else:
                self.client.io.loop.call_soon_threadsafe(
                    self._flush_done_threadsafe)
        except RuntimeError:
            pass  # io loop torn down (shutdown): parity with send_nowait

    def _flush_done_threadsafe(self) -> None:
        with self._done_lock:
            items, self._done_buf = self._done_buf, []
            self._done_scheduled = False
        if not items:
            return
        msg = items[0] if len(items) == 1 else {"kind": "task_done_batch",
                                                "items": items}
        try:
            self.client.send_nowait(msg)
        except Exception:
            pass

    def _record_phases(self, spec: Dict[str, Any], outcome: str) -> None:
        """Finalize + buffer this task's phase event (flight recorder).
        Pops ``__ph__`` so a completion that re-routes (store failure →
        _complete_error) records exactly once."""
        ph = spec.pop("__ph__", None)
        if ph is None:
            return
        end = time.time()
        if "exec_start" in ph and "exec_s" not in ph:
            ph["exec_s"] = max(0.0, end - ph.pop("exec_start"))
        ph.pop("exec_start", None)
        task_events.record({
            "task_id": spec.get("task_id"),
            "label": spec.get("label"),
            "actor_id": spec.get("actor_id"),
            "worker_id": self.worker_id,
            "node_id": self.node_id,
            "start_ts": ph.pop("start_ts"),
            "end_ts": end,
            "outcome": outcome,
            "phases": {k: v for k, v in ph.items()
                       if k in task_events.PHASE_KEYS},
        })

    def _complete_ok(self, spec: Dict[str, Any], result: Any) -> None:
        ph = spec.get("__ph__")
        t_store = 0.0
        if ph is not None:
            t_store = time.time()
            if "exec_start" in ph:
                ph["exec_s"] = max(0.0, t_store - ph.pop("exec_start"))
        try:
            locations = self._store_returns(spec, result)
        except BaseException as e:  # noqa: BLE001
            self._complete_error(spec, e, traceback.format_exc())
            return
        if ph is not None:
            ph["result_store_s"] = max(0.0, time.time() - t_store)
        self._record_phases(spec, "finished")
        mb = spec.pop("__mb__", None)
        if mb is not None:
            # Journal BEFORE the caller can observe the result: a duplicate
            # arriving right after the reply must hit the journal.
            mb.note_result(spec, {"locations": locations})
        msg = {
            "kind": "task_done",
            "task_id": spec["task_id"],
            "worker_id": self.worker_id,
            "locations": locations,
        }
        self._finish_direct(spec, {"locations": locations})
        if spec.pop("__leased__", False):
            # The controller never saw this (directly-pushed) spec; ship it
            # with the completion so lineage + task events stay complete.
            # Fully-inline results need no lineage — the location the
            # controller stores CARRIES the bytes, so the object can never
            # need reconstruction; a slim spec (ids + label) keeps the
            # task-event trail while skipping the args/closure payload and
            # the controller-side lineage write on the hot path.
            if all(loc.inline is not None for loc in locations):
                msg["spec"] = {"task_id": spec["task_id"],
                               "label": spec.get("label"),
                               "return_ids": spec["return_ids"]}
            else:
                msg["spec"] = {k: v for k, v in spec.items()
                               if not k.startswith("__")}
            msg["started_ts"] = spec.get("__start_ts__")
        # Fire-and-forget: nothing consumes the ack, and the worker is not
        # eligible for new work until the controller processes this message
        # anyway (state flips to idle there) — so dropping the round trip
        # costs nothing and saves a response pickle + wakeup per task.
        self._ship_done(msg)

    def _complete_error(self, spec: Dict[str, Any], e: BaseException, tb: str) -> None:
        self._record_phases(spec, "failed")
        label = spec.get("label", spec["task_id"][:8])
        err = TaskError(label, e, tb)
        try:
            data = pickle.dumps(err)
        except Exception:
            # Unpicklable cause (socket, lock, ...): degrade to a string
            # rendition so the error still reaches the caller instead of
            # hanging the task forever.
            err = TaskError(label, RuntimeError(f"{type(e).__name__}: {e}"), tb)
            data = pickle.dumps(err)
        err_ids = list(spec["return_ids"])
        if not err_ids and spec.get("streaming"):
            # Streaming tasks have no pre-allocated return ids; ship the
            # error as a synthetic location so the consumer sees the real
            # exception on next() rather than a generic crash.
            from .ids import ObjectID

            err_ids = [ObjectID.generate()]
        err_locs = [
            ObjectLocation(object_id=oid, size=len(data), inline=data, is_error=True)
            for oid in err_ids
        ]
        mb = spec.pop("__mb__", None)
        if mb is not None:
            # Errors journal too: the call WAS applied (it raised) — a
            # replayed duplicate must observe the same exception, not
            # re-execute the method.
            mb.note_result(spec, {"error_locations": err_locs,
                                  "is_error": True})
        msg = {
            "kind": "task_done",
            "task_id": spec["task_id"],
            "worker_id": self.worker_id,
            "error_locations": err_locs,
            "is_error": True,
        }
        self._finish_direct(spec, {"error_locations": err_locs})
        if spec.pop("__leased__", False):
            msg["spec"] = {k: v for k, v in spec.items()
                           if not k.startswith("__")}
            msg["started_ts"] = spec.get("__start_ts__")
        try:
            self._ship_done(msg)
        except Exception:
            pass

    def _complete_replayed(self, spec: Dict[str, Any],
                           payload: Dict[str, Any]) -> None:
        """A deduped duplicate of an already-applied call: republish the
        journaled outcome — locations or error — without re-executing
        (exactly-once replay). The task_done retires a controller-path
        resubmission of the same task_id; the location store is idempotent,
        so replying twice is safe."""
        self._finish_direct(spec, payload)
        msg = {"kind": "task_done", "task_id": spec["task_id"],
               "worker_id": self.worker_id}
        msg.update(payload)
        spec.pop("__leased__", None)
        try:
            self._ship_done(msg)
        except Exception:
            pass

    def _run_streaming(self, spec: Dict[str, Any], result: Any) -> None:
        """Drive a generator task: each yielded value becomes its own object,
        reported immediately (reference: streaming generator protocol,
        _raylet.pyx:273 execute_streaming_generator). The controller holds
        the report reply while the consumer lags past the backpressure
        window, so this thread self-throttles."""
        import inspect

        from .ids import ObjectID

        task_id = spec["task_id"]
        if not inspect.isgenerator(result):
            raise TypeError(
                f"num_returns='streaming' requires a generator function, "
                f"got {type(result).__name__}"
            )
        for value in result:
            oid = ObjectID.generate()
            loc = put_bytes(value, oid, self.node_id)
            ack = self.client.request(
                {"kind": "generator_item", "task_id": task_id, "loc": loc}
            )
            if isinstance(ack, dict) and ack.get("closed"):
                # Consumer dropped the generator: stop producing.
                result.close()
                break
        self._record_phases(spec, "finished")
        self.client.request(
            {
                "kind": "task_done",
                "task_id": task_id,
                "worker_id": self.worker_id,
                "locations": [],
            }
        )

    def _run_streaming_async(self, spec: Dict[str, Any], agen: Any,
                             mailbox: "ActorMailbox") -> None:
        """Drive an async generator on the actor's persistent loop; item
        reports run in the default executor so awaits keep interleaving."""
        import asyncio

        from .ids import ObjectID

        loop = mailbox.ensure_aio_loop()
        task_id = spec["task_id"]

        async def drive():
            try:
                async for value in agen:
                    oid = ObjectID.generate()
                    loc = put_bytes(value, oid, self.node_id)
                    ack = await asyncio.get_running_loop().run_in_executor(
                        None,
                        lambda loc=loc: self.client.request(
                            {"kind": "generator_item", "task_id": task_id,
                             "loc": loc}
                        ),
                    )
                    if isinstance(ack, dict) and ack.get("closed"):
                        await agen.aclose()
                        break
            except BaseException as e:  # noqa: BLE001
                self._complete_error(spec, e, traceback.format_exc())
                return
            self._record_phases(spec, "finished")
            await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: self.client.request(
                    {"kind": "task_done", "task_id": task_id,
                     "worker_id": self.worker_id, "locations": []}
                ),
            )

        asyncio.run_coroutine_threadsafe(drive(), loop)

    def _store_returns(self, spec: Dict[str, Any], result: Any) -> List[ObjectLocation]:
        return_ids: List[str] = spec["return_ids"]
        if not return_ids:
            return []
        if len(return_ids) == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != len(return_ids):
                raise ValueError(
                    f"task declared num_returns={len(return_ids)} but returned {len(values)}"
                )
        return [put_bytes(v, oid, self.node_id) for v, oid in zip(values, return_ids)]

    def _restore_record(self, spec: Dict[str, Any],
                        mb: "ActorMailbox") -> Optional[Dict[str, Any]]:
        """Newest reachable checkpoint/snapshot record for this actor: the
        controller-shipped blob riding the spec vs a (possibly newer)
        host-local checkpoint file — epochs are monotonic across hosts, so
        the comparison is one int. None -> run the constructor."""
        from . import checkpoint

        blob = spec.get("state_blob")
        rec: Optional[Dict[str, Any]] = None
        if blob is not None:
            rec = checkpoint.decode(blob)
        if mb.ckpt_enabled:
            local = checkpoint.newest_local(mb.actor_id)
            if local is not None and local[0] > (rec or {}).get("epoch", 0):
                try:
                    rec = checkpoint.decode(local[1])
                except Exception:
                    pass  # torn/stale file: the shipped copy (or ctor) wins
        return rec

    def _instantiate_actor(self, spec: Dict[str, Any]) -> None:
        actor_id = spec["actor_id"]
        mb = ActorMailbox(self, actor_id, spec.get("max_concurrency", 1))
        mb.spec = spec  # kept for re-claiming the actor after a controller bounce
        mb.configure(spec)
        self.actors[actor_id] = mb
        if mb.ckpt_enabled and mb.ckpt_interval > 0.0:
            self._ensure_ckpt_timer()

        def create():
            from . import ownership

            _held = ownership.acquire_spec_refs(spec)  # noqa: F841
            try:
                # Set before instantiating: constructors may legitimately
                # ask for their own id (ray parity: get_runtime_context()
                # works inside __init__), and threads an actor spawns from
                # its constructor inherit it by copying.
                ctx.task_local.actor_id = actor_id
                rec = self._restore_record(spec, mb)
                restored_epoch = None
                if rec is not None:
                    # Drain migration or crash restart: restore the newest
                    # reachable record instead of re-running the
                    # constructor — the actor arrives with state AND its
                    # exactly-once journal intact.
                    mb.instance = rec["instance"]
                    mb.ckpt_epoch = int(rec.get("epoch", 0))
                    if rec.get("journal"):
                        # Call-replay dedup entries only matter when replay
                        # is armed, but __dag__* entries (a compiled DAG's
                        # per-stage seq journal) must survive the restore
                        # regardless — DAG recovery resumes from them.
                        with mb._seq_lock:
                            mb.journal = {
                                c: dict(e)
                                for c, e in rec["journal"].items()
                                if mb.replay or c.startswith("__dag__")}
                    restored_epoch = mb.ckpt_epoch
                else:
                    cls = self._load_function(spec["func_id"])
                    args, kwargs = self._resolve_args(spec)
                    mb.instance = cls(*args, **kwargs)
                ready: Dict[str, Any] = {"kind": "actor_ready",
                                         "actor_id": actor_id}
                if restored_epoch is not None:
                    ready["restored_epoch"] = restored_epoch
                self.client.request(ready)
            except BaseException as e:  # noqa: BLE001
                tb = traceback.format_exc()
                self.client.request(
                    {
                        "kind": "actor_error",
                        "actor_id": actor_id,
                        "error": ActorDiedError(f"actor constructor failed: {e!r}\n{tb}"),
                    }
                )

        # __init__ runs on the mailbox thread so actor state is thread-affine.
        mb.q.put({"__create__": create})

    def _ensure_ckpt_timer(self) -> None:
        """One daemon sweep thread for interval-based checkpoints, started
        lazily at the first hosted actor with checkpoint_interval_s — a
        worker hosting none never grows the thread."""
        if getattr(self, "_ckpt_timer_started", False):
            return
        self._ckpt_timer_started = True

        def _tick() -> None:
            while not self.shutdown_event.is_set():
                time.sleep(flags.get("RTPU_CHECKPOINT_TICK_S"))
                for mb in list(self.actors.values()):
                    try:
                        if mb.ckpt_due():
                            mb.request_checkpoint()
                    except Exception:
                        pass  # checkpointing must never hurt the actor

        threading.Thread(target=_tick, name="ckpt-timer",
                         daemon=True).start()

    def serve_forever(self) -> None:
        self.shutdown_event.wait()
        if self._log_attributor is not None:
            try:
                self._log_attributor.flush()
            except Exception:
                pass
        try:
            self.client.close()
        except Exception:
            pass
        # Hard-exit: executor threads are non-daemon and user task code may be
        # mid-flight; a worker told to shut down must actually die (the
        # reference's raylet SIGTERMs its workers for the same reason).
        os._exit(0)


def _is_coroutine(x: Any) -> bool:
    import inspect

    return inspect.iscoroutine(x)


def _is_async_gen(x: Any) -> bool:
    import inspect

    return inspect.isasyncgen(x)
