"""In-controller metrics history + alert rules (the cluster telemetry plane).

Parity target: the reference dashboard's built-in time-series view
(dashboard agents -> GCS -> dashboard head) and its alerting hooks. Here
the controller is already the aggregation point for every metric family —
its own ``rtpu_*`` gauges/counters/histograms plus the app metrics shipped
by ``util/metrics.py`` — so history is a fixed-step ring buffer sampled
in-process each ``RTPU_TSDB_STEP_S`` and served by the ``query_metrics``
RPC. No Prometheus server, no sidecar: `rtpu top` and the dashboard
sparklines read the same ring.

Counters are stored cumulative and converted to per-second rates at query
time (clamped at zero so a controller bounce's counter reset never shows
as a negative spike). Histograms are stored as cumulative bucket states;
a query derives p50/p99/mean/rate over a trailing window by differencing
the cumulative state across the window and interpolating inside the
winning bucket (the PromQL histogram_quantile scheme, reusing the
controller's ``_hist_quantile``).

The ring (and the alert engine's firing state) pickles beside
``--state-path`` so history survives a controller bounce with a gap
bounded by the downtime, and an alert that was firing does not re-fire
after the restart.
"""
from __future__ import annotations

import json
import logging
import os
import pickle
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_TagTuple = Tuple[Tuple[str, str], ...]
_SeriesKey = Tuple[str, _TagTuple]

# Backstop against unbounded label cardinality (e.g. per-pid worker gauges
# on a churning cluster): once the ring holds this many distinct series,
# new keys are dropped rather than grown.
MAX_SERIES = 4096

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


def _hist_quantile(bounds: List[float], h: Dict[str, Any], q: float) -> float:
    # Same linear interpolation as controller._hist_quantile; duplicated
    # here (12 lines) rather than importing the controller module into the
    # telemetry unit tests.
    total = h.get("count", 0)
    if not total:
        return 0.0
    target = q * total
    cum = 0.0
    lo = 0.0
    for i, b in enumerate(bounds):
        c = h["buckets"][i]
        if c and cum + c >= target:
            return lo + (b - lo) * ((target - cum) / c)
        cum += c
        lo = b
    return bounds[-1] if bounds else 0.0


def _hist_delta(new: Dict[str, Any], old: Optional[Dict[str, Any]]) -> dict:
    """Cumulative histogram state difference new - old (old=None => new).
    A counter reset (controller bounce) shows as any negative component;
    the caller treats the delta as the full new state then."""
    if old is None:
        return {"buckets": list(new["buckets"]), "sum": new["sum"],
                "count": new["count"]}
    if (len(old["buckets"]) != len(new["buckets"])
            or new["count"] < old["count"]):
        return {"buckets": list(new["buckets"]), "sum": new["sum"],
                "count": new["count"]}
    return {
        "buckets": [max(0, n - o)
                    for n, o in zip(new["buckets"], old["buckets"])],
        "sum": max(0.0, new["sum"] - old["sum"]),
        "count": new["count"] - old["count"],
    }


class MetricsTSDB:
    """Fixed-step ring of every metric family the controller can see.

    ``sample(now, families)`` appends one point per (name, tags) series;
    ``query(...)`` returns plottable [t, value] series with counter->rate
    and histogram->p50/p99/mean/rate derivation done server-side so
    consumers (rtpu top, dashboard sparklines, alert rules) never touch
    bucket math.
    """

    def __init__(self, step_s: float, retain: int,
                 persist_path: Optional[str] = None,
                 persist_every_s: float = 0.0) -> None:
        self.step_s = max(0.05, float(step_s))
        self.retain = max(2, int(retain))
        self.persist_path = persist_path
        self.persist_every_s = float(persist_every_s)
        self._last_persist = 0.0
        # key -> {"type", "boundaries", "points": deque[(ts, value)]}
        # gauge/counter points hold floats; histogram points hold the
        # cumulative {"buckets", "sum", "count"} state at sample time.
        self.series: Dict[_SeriesKey, dict] = {}
        self.restored_alert_state: Dict[Any, dict] = {}
        if persist_path:
            self._load()

    # ------------------------------------------------------------- sampling

    def sample(self, now: float, families: Dict[str, dict]) -> None:
        for name, fam in families.items():
            ftype = fam.get("type", "gauge")
            bounds = list(fam.get("boundaries") or ())
            for tags, value in fam.get("data", {}).items():
                key = (name, tuple(tags))
                ser = self.series.get(key)
                if ser is None:
                    if len(self.series) >= MAX_SERIES:
                        continue
                    ser = self.series[key] = {
                        "type": ftype, "boundaries": bounds,
                        "points": deque(maxlen=self.retain)}
                if isinstance(value, dict):
                    # Histogram: the aggregator mutates its state in place;
                    # snapshot a copy or every ring point aliases "now".
                    value = {"buckets": list(value.get("buckets", ())),
                             "sum": float(value.get("sum", 0.0)),
                             "count": int(value.get("count", 0))}
                else:
                    value = float(value)
                ser["points"].append((now, value))

    # -------------------------------------------------------------- queries

    def query(self, name: Optional[str] = None,
              prefix: Optional[str] = None,
              tags: Optional[Dict[str, str]] = None,
              since: Optional[float] = None,
              stat: Optional[str] = None,
              window_s: float = 60.0,
              limit_series: int = 64) -> List[dict]:
        """Plottable series. ``stat`` picks the derived statistic for
        histograms ("p50" | "p99" | "mean" | "rate"; default emits p50 and
        p99 series) and is ignored for gauges; counters always emit their
        per-second rate plus a final cumulative "total" field."""
        out: List[dict] = []
        want_tags = tuple(sorted((tags or {}).items()))
        for (mname, mtags), ser in self.series.items():
            if name is not None and mname != name:
                continue
            if prefix is not None and not mname.startswith(prefix):
                continue
            if want_tags and not set(want_tags) <= set(mtags):
                continue
            pts = [p for p in ser["points"]
                   if since is None or p[0] >= since]
            if not pts:
                continue
            base = {"name": mname, "tags": dict(mtags),
                    "type": ser["type"]}
            if ser["type"] == "counter":
                out.append(dict(base, stat="rate",
                                total=pts[-1][1],
                                points=self._rate_points(pts)))
            elif ser["type"] == "histogram":
                stats = [stat] if stat else ["p50", "p99"]
                for st in stats:
                    out.append(dict(base, stat=st,
                                    points=self._hist_points(
                                        ser, pts, st, window_s)))
            else:
                out.append(dict(base, stat="value",
                                points=[[t, v] for t, v in pts]))
            if len(out) >= limit_series:
                break
        out.sort(key=lambda s: (s["name"], sorted(s["tags"].items()),
                                s.get("stat", "")))
        return out

    def latest(self, name: str, tags: Optional[Dict[str, str]] = None,
               stat: Optional[str] = None,
               window_s: float = 60.0) -> List[Tuple[dict, float]]:
        """(series-descriptor, latest-value) pairs — the alert engine's
        view. Histograms default to p99 here, not the p50+p99 pair."""
        st = stat or "p99"
        res = []
        for ser in self.query(name=name, tags=tags, stat=st,
                              window_s=window_s):
            if ser["points"]:
                res.append((ser, ser["points"][-1][1]))
        return res

    def _rate_points(self, pts: List[Tuple[float, float]]) -> List[list]:
        out = []
        for i in range(1, len(pts)):
            dt = pts[i][0] - pts[i - 1][0]
            if dt <= 0:
                continue
            out.append([pts[i][0],
                        max(0.0, (pts[i][1] - pts[i - 1][1]) / dt)])
        return out

    def _hist_points(self, ser: dict, pts: List[Tuple[float, Any]],
                     stat: str, window_s: float) -> List[list]:
        bounds = ser["boundaries"]
        out = []
        for i, (t, cum) in enumerate(pts):
            # Trailing window: difference against the newest point at or
            # before t - window_s (absent for early points => since start).
            old = None
            for j in range(i - 1, -1, -1):
                if pts[j][0] <= t - window_s:
                    old = pts[j]
                    break
            d = _hist_delta(cum, old[1] if old else None)
            if stat == "rate":
                dt = (t - old[0]) if old else window_s
                v = d["count"] / dt if dt > 0 else 0.0
            elif stat == "mean":
                v = d["sum"] / d["count"] if d["count"] else 0.0
            elif stat == "p50":
                v = _hist_quantile(bounds, d, 0.5)
            else:
                v = _hist_quantile(bounds, d, 0.99)
            out.append([t, v])
        return out

    # -------------------------------------------------------- persistence

    def save(self, alert_state: Optional[Dict[Any, dict]] = None) -> None:
        if not self.persist_path:
            return
        payload = {
            "v": 1,
            "step_s": self.step_s,
            "series": [
                {"name": k[0], "tags": list(k[1]), "type": s["type"],
                 "boundaries": s["boundaries"],
                 "points": list(s["points"])}
                for k, s in self.series.items()
            ],
            "alerts": alert_state or {},
        }
        tmp = self.persist_path + f".tmp{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(payload, f)
            os.replace(tmp, self.persist_path)
        except Exception:
            logger.debug("tsdb persist failed", exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def maybe_persist(self, now: float,
                      alert_state: Optional[Dict[Any, dict]] = None) -> None:
        if not self.persist_path or self.persist_every_s <= 0:
            return
        if now - self._last_persist >= self.persist_every_s:
            self._last_persist = now
            self.save(alert_state)

    def _load(self) -> None:
        try:
            with open(self.persist_path, "rb") as f:
                payload = pickle.load(f)
        except FileNotFoundError:
            return
        except Exception:
            logger.warning("tsdb restore failed; starting empty",
                           exc_info=True)
            return
        for ser in payload.get("series", ()):
            key = (ser["name"], tuple(tuple(t) for t in ser["tags"]))
            if len(self.series) >= MAX_SERIES:
                break
            self.series[key] = {
                "type": ser["type"], "boundaries": ser["boundaries"],
                "points": deque(ser["points"], maxlen=self.retain)}
        self.restored_alert_state = payload.get("alerts", {})


# ---------------------------------------------------------------- alerting

# Threshold + for-duration rules, the Prometheus alerting-rule shape
# evaluated in-process over the ring. Defaults catch the regressions the
# ROADMAP cares about without any configuration; RTPU_ALERT_RULES merges
# user rules over these by name ({"name": ..., "disabled": true} removes).
DEFAULT_ALERT_RULES: List[dict] = [
    {"name": "queue_wait_p99_high", "metric": "rtpu_task_queue_wait_s",
     "stat": "p99", "op": ">", "threshold": 5.0, "for_s": 10.0,
     "severity": "WARNING",
     "message": "task queue-wait p99 above 5s — cluster saturated"},
    {"name": "node_mem_high", "metric": "rtpu_node_mem_fraction",
     "op": ">", "threshold": 0.92, "for_s": 30.0, "severity": "WARNING",
     "message": "node memory above 92% — OOM-kill risk"},
    {"name": "suspect_nodes", "metric": "rtpu_nodes",
     "tags": {"state": "suspect"}, "op": ">", "threshold": 0.0,
     "for_s": 0.0, "severity": "ERROR",
     "message": "node(s) missing heartbeats (suspect)"},
    {"name": "serve_shed_rate_high", "metric": "rtpu_serve_shed_total",
     "stat": "rate", "op": ">", "threshold": 1.0, "for_s": 10.0,
     "severity": "WARNING",
     "message": "serve shedding >1 req/s for 10s — sustained overload "
                "(queue_full / breaker_open)"},
    {"name": "serve_ttft_p99_high", "metric": "rtpu_serve_ttft_s",
     "stat": "p99", "op": ">", "threshold": 5.0, "for_s": 15.0,
     "severity": "WARNING",
     "message": "serve TTFT p99 above 5s for 15s — scale the pool or "
                "shed load (queue wait is counted since arrival)"},
    {"name": "object_store_mem_high",
     "metric": "rtpu_object_store_fill_fraction",
     "op": ">", "threshold": 0.9, "for_s": 10.0, "severity": "WARNING",
     "message": "object arena above 90% full for 10s — spill pressure; "
                "run `rtpu memory --group-by owner` to find the holder"},
    {"name": "dag_stage_starved", "metric": "rtpu_dag_stage_busy_fraction",
     "tags": {"phase": "recv"}, "op": ">", "threshold": 0.9,
     "for_s": 30.0, "severity": "WARNING",
     "message": "compiled-DAG stage starved >90% of wall time for 30s — "
                "an upstream stage is the bottleneck; run `rtpu dag "
                "stats` for the attribution"},
    {"name": "dag_edge_stalled", "metric": "rtpu_dag_edge_blocked_fraction",
     "op": ">", "threshold": 0.9, "for_s": 30.0, "severity": "WARNING",
     "message": "compiled-DAG edge writer blocked on ring space >90% of "
                "wall time for 30s — the consumer stage cannot keep up; "
                "run `rtpu dag stats` for the attribution"},
    {"name": "serve_slo_miss_rate_high",
     "metric": "rtpu_serve_slo_miss_total",
     "stat": "rate", "op": ">", "threshold": 0.5, "for_s": 15.0,
     "severity": "WARNING",
     "message": "serve SLO misses >0.5 req/s for 15s — requests over "
                "RTPU_SERVE_SLO_MS (or shed / deadline-exceeded); the "
                "offending rows are retained in the request ledger: "
                "`rtpu serve requests --status deadline` / "
                "`rtpu serve trace REQUEST_ID` for the hop breakdown"},
    {"name": "job_flapping", "metric": "rtpu_job_attempts_total",
     "stat": "rate", "op": ">", "threshold": 0.2, "for_s": 30.0,
     "severity": "WARNING",
     "message": "job entrypoints relaunching >0.2/s for 30s — a job is "
                "crash-looping through its retry budget; check `rtpu job "
                "list` and the JOB_RETRYING events for the cause"},
]


def load_alert_rules(spec: Optional[str]) -> List[dict]:
    """DEFAULT_ALERT_RULES overlaid by the RTPU_ALERT_RULES JSON list,
    merged by rule name. A malformed spec logs and keeps the defaults —
    alerting config must never take the controller down."""
    rules = {r["name"]: dict(r) for r in DEFAULT_ALERT_RULES}
    if spec:
        try:
            user = json.loads(spec)
            if not isinstance(user, list):
                raise ValueError("RTPU_ALERT_RULES must be a JSON list")
            for r in user:
                if not isinstance(r, dict) or not r.get("name"):
                    raise ValueError("each rule needs a name")
                merged = dict(rules.get(r["name"], {}), **r)
                rules[r["name"]] = merged
        except Exception:
            logger.warning("bad RTPU_ALERT_RULES; using defaults",
                           exc_info=True)
    out = []
    for r in rules.values():
        if r.get("disabled"):
            continue
        if not r.get("metric") or "threshold" not in r:
            logger.warning("alert rule %r missing metric/threshold; "
                           "skipped", r.get("name"))
            continue
        out.append(r)
    return out


class AlertEngine:
    """Evaluates rules over the TSDB each sampling step.

    Per (rule, series) state machine: condition true -> pending; pending
    for ``for_s`` -> ALERT_FIRING (once); condition false or series gone
    -> ALERT_RESOLVED (once, only if it fired). State snapshots into the
    TSDB persist file so a bounced controller neither duplicates the
    FIRING event nor forgets to RESOLVE.
    """

    def __init__(self, rules: List[dict],
                 emit_fn: Callable[..., None]) -> None:
        self.rules = rules
        self.emit = emit_fn
        # (rule_name, tags_tuple) -> {"pending_since": ts|None,
        #                             "firing": bool, "value": float}
        self.state: Dict[Tuple[str, _TagTuple], dict] = {}

    def evaluate(self, now: float, tsdb: MetricsTSDB) -> None:
        for rule in self.rules:
            op = _OPS.get(rule.get("op", ">"), _OPS[">"])
            thresh = float(rule["threshold"])
            for_s = float(rule.get("for_s", 0.0))
            hits = tsdb.latest(rule["metric"], tags=rule.get("tags"),
                               stat=rule.get("stat"),
                               window_s=float(rule.get("window_s", 60.0)))
            seen = set()
            for ser, value in hits:
                key = (rule["name"], tuple(sorted(ser["tags"].items())))
                seen.add(key)
                st = self.state.setdefault(
                    key, {"pending_since": None, "firing": False,
                          "value": 0.0})
                st["value"] = value
                if op(value, thresh):
                    if st["pending_since"] is None:
                        st["pending_since"] = now
                    if (not st["firing"]
                            and now - st["pending_since"] >= for_s):
                        st["firing"] = True
                        self._emit_firing(rule, ser, value)
                else:
                    self._clear(rule, key, st)
            # A series that stopped reporting (node gone, label idle past
            # retention) resolves rather than staying firing forever.
            for key, st in self.state.items():
                if key[0] == rule["name"] and key not in seen:
                    self._clear(rule, key, st,
                                tags=dict(key[1]))

    def _clear(self, rule: dict, key, st: dict,
               tags: Optional[dict] = None) -> None:
        st["pending_since"] = None
        if st["firing"]:
            st["firing"] = False
            t = tags if tags is not None else dict(key[1])
            self.emit("INFO", "ALERT_RESOLVED",
                      f"alert {rule['name']} resolved "
                      f"({self._series_label(rule, t)})",
                      data={"alert": rule["name"], "tags": t,
                            "value": st.get("value", 0.0)})

    def _emit_firing(self, rule: dict, ser: dict, value: float) -> None:
        msg = rule.get("message") or (
            f"{rule['metric']} {rule.get('op', '>')} {rule['threshold']}")
        self.emit(rule.get("severity", "WARNING"), "ALERT_FIRING",
                  f"alert {rule['name']}: {msg} "
                  f"({self._series_label(rule, ser['tags'])}, "
                  f"value={value:.4g})",
                  data={"alert": rule["name"], "tags": ser["tags"],
                        "value": value,
                        "threshold": rule["threshold"],
                        "metric": rule["metric"]})

    @staticmethod
    def _series_label(rule: dict, tags: dict) -> str:
        inner = ",".join(f"{k}={v}" for k, v in sorted(tags.items()))
        return f"{rule['metric']}{{{inner}}}" if inner else rule["metric"]

    # ------------------------------------------------------- persistence

    def snapshot(self) -> Dict[Any, dict]:
        return {k: dict(v) for k, v in self.state.items()}

    def restore(self, snap: Dict[Any, dict]) -> None:
        names = {r["name"] for r in self.rules}
        for k, v in (snap or {}).items():
            try:
                if k[0] in names:
                    self.state[(k[0], tuple(tuple(t) for t in k[1]))] = \
                        dict(v)
            except Exception:
                continue

    def firing(self) -> List[dict]:
        out = []
        for (name, tags), st in self.state.items():
            if st.get("firing"):
                out.append({"alert": name, "tags": dict(tags),
                            "value": st.get("value", 0.0)})
        return out
