"""Cluster event subsystem: structured control-plane events.

Role-equivalent to the reference's cluster-event framework (ray:
src/ray/gcs/gcs_server/gcs_ray_event_converter.h + the
``ray list cluster-events`` state API and the dashboard event feed): node,
actor, task, placement-group, and autoscaler lifecycle transitions become
structured records — (ts, severity, source, kind, entity ids, message,
data) — instead of lines scattered through the controller's stderr.

Three pieces live here:

- :func:`make_event` — the one record shape every producer emits.
- :class:`EventLog` — the controller-side store: a bounded ring served by
  the ``get_events`` RPC (severity/kind/entity/since filters plus
  long-poll follow), JSONL persistence alongside ``--state-path`` so the
  feed survives a controller bounce, and per-(source, severity) counters
  feeding the ``rtpu_events_total`` metric.
- a worker/driver-side shipper — :func:`emit` buffers events in a bounded
  deque and a daemon flusher ships batches over the process's
  reconnecting control connection (the same reconnect-safe pattern as
  ``task_events.py``: a batch in flight when the controller dies delivers
  to the restarted controller). Host agents ship their events themselves
  on the heartbeat path (they hold a raw protocol connection, not a
  CoreClient).

Everything is gated on ``RTPU_EVENTS``: when off, emit sites pay one flag
check and nothing is stored, persisted, or shipped.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu import flags

# Severity ladder (reference: event.proto severity levels). Filters treat
# a requested severity as the MINIMUM level to return.
SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}


def severity_rank(severity: Optional[str]) -> int:
    """Rank for min-severity filtering; unknown severities rank as INFO."""
    return _SEV_RANK.get((severity or "INFO").upper(), 1)


def enabled() -> bool:
    return bool(flags.get("RTPU_EVENTS"))


def make_event(severity: str, source: str, kind: str, message: str, *,
               node_id: Optional[str] = None,
               worker_id: Optional[str] = None,
               actor_id: Optional[str] = None,
               task_id: Optional[str] = None,
               data: Optional[Dict[str, Any]] = None,
               ts: Optional[float] = None) -> Dict[str, Any]:
    """One structured cluster event. ``kind`` is a stable SCREAMING_SNAKE
    identifier (NODE_DIED, TASK_HUNG, ...); ``message`` is the human line;
    ``data`` carries kind-specific payload (e.g. the captured stack)."""
    return {
        "ts": ts if ts is not None else time.time(),
        "severity": (severity or "INFO").upper(),
        "source": source,
        "kind": kind,
        "message": message,
        "node_id": node_id,
        "worker_id": worker_id,
        "actor_id": actor_id,
        "task_id": task_id,
        "data": data or {},
    }


class EventLog:
    """Controller-side event store: bounded ring + JSONL persistence.

    Events get a monotonically increasing ``seq`` — the follow cursor for
    ``get_events(after_seq=...)`` long-polls. With a persist path, every
    event appends one JSON line and a restart reloads the ring tail (seq
    continues from the persisted maximum, so follower cursors stay valid
    across a controller bounce).
    """

    def __init__(self, maxlen: int = 10000,
                 persist_path: Optional[str] = None):
        self.ring: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=max(16, int(maxlen)))
        self.persist_path = persist_path
        self.seq = 0
        # (source, severity) -> count since start/restore: the
        # rtpu_events_total{source,severity} counter.
        self.counts: Dict[tuple, int] = {}
        self._file: Any = None  # lazily opened; False = disabled on error
        # Follow waiters: asyncio.Events set (once each) on every append.
        self._waiters: List[Any] = []
        self._restore()

    # ------------------------------------------------------------ persistence

    def _restore(self) -> None:
        if not self.persist_path or not os.path.exists(self.persist_path):
            return
        tail: "collections.deque[str]" = collections.deque(
            maxlen=self.ring.maxlen)
        try:
            with open(self.persist_path, "r", encoding="utf-8",
                      errors="replace") as f:
                for line in f:
                    if line.strip():
                        tail.append(line)
        except OSError:
            return
        for line in tail:
            try:
                ev = json.loads(line)
            except ValueError:
                continue  # torn write at the kill point: skip the line
            if not isinstance(ev, dict) or "kind" not in ev:
                continue
            self.seq = max(self.seq, int(ev.get("seq", 0)))
            self.ring.append(ev)
            key = (ev.get("source", "?"), ev.get("severity", "INFO"))
            self.counts[key] = self.counts.get(key, 0) + 1

    def _persist(self, ev: Dict[str, Any]) -> None:
        if not self.persist_path:
            return
        if self._file is None:
            try:
                self._file = open(self.persist_path, "a", buffering=1,
                                  encoding="utf-8")
            except OSError:
                self._file = False
        if self._file is False:
            return
        try:
            self._file.write(json.dumps(ev, default=str) + "\n")
        except Exception:
            self._file = False  # never let the event feed hurt the plane

    # ----------------------------------------------------------------- append

    def append(self, ev: Dict[str, Any]) -> Dict[str, Any]:
        self.seq += 1
        ev["seq"] = self.seq
        self.ring.append(ev)
        key = (ev.get("source", "?"), ev.get("severity", "INFO"))
        self.counts[key] = self.counts.get(key, 0) + 1
        self._persist(ev)
        waiters, self._waiters = self._waiters, []
        for w in waiters:
            try:
                w.set()
            except Exception:
                pass
        return ev

    def emit(self, severity: str, kind: str, message: str,
             source: str = "controller", **entities: Any) -> None:
        if not enabled():
            return
        self.append(make_event(severity, source, kind, message, **entities))

    # ------------------------------------------------------------------ query

    def query(self, severity: Optional[str] = None,
              kinds: Optional[List[str]] = None,
              task_id: Optional[str] = None,
              actor_id: Optional[str] = None,
              node_id: Optional[str] = None,
              worker_id: Optional[str] = None,
              since: Optional[float] = None,
              after_seq: Optional[int] = None,
              limit: int = 1000) -> List[Dict[str, Any]]:
        """Filtered view of the ring, oldest first. ``severity`` is a
        minimum level; ``kinds`` matches exactly (case-insensitive);
        entity ids match on PREFIX so the short ids `rtpu status` prints
        work; ``since`` is a wall-clock lower bound; ``after_seq`` the
        follow cursor."""
        min_rank = severity_rank(severity) if severity else 0
        want_kinds = {k.upper() for k in kinds} if kinds else None
        out: List[Dict[str, Any]] = []
        for ev in self.ring:
            if after_seq is not None and ev.get("seq", 0) <= after_seq:
                continue
            if since is not None and ev.get("ts", 0.0) < since:
                continue
            if min_rank and severity_rank(ev.get("severity")) < min_rank:
                continue
            if want_kinds and (ev.get("kind") or "").upper() not in want_kinds:
                continue
            if task_id and not (ev.get("task_id") or "").startswith(task_id):
                continue
            if actor_id and not (ev.get("actor_id") or "").startswith(
                    actor_id):
                continue
            if node_id and not (ev.get("node_id") or "").startswith(node_id):
                continue
            if worker_id and not (ev.get("worker_id") or "").startswith(
                    worker_id):
                continue
            out.append(ev)
        return out[-max(1, int(limit)):]

    async def wait_for_new(self, timeout: float) -> None:
        """Block (on the controller's event loop) until any event appends
        or the timeout passes — the get_events long-poll primitive."""
        import asyncio

        ev = asyncio.Event()
        self._waiters.append(ev)
        try:
            await asyncio.wait_for(ev.wait(), max(0.0, timeout) or 1e-6)
        except asyncio.TimeoutError:
            pass
        finally:
            try:
                self._waiters.remove(ev)
            except ValueError:
                pass


# --------------------------------------------------- worker/driver shipping


class _Shipper:
    """Bounded per-process event buffer flushed to the controller over the
    reconnecting control connection (same daemon-flusher shape as
    task_events._Recorder — a batch that fails to deliver re-buffers and
    lands on the restarted controller after re-register)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.events: Optional[collections.deque] = None
        self._thread: Optional[threading.Thread] = None

    def emit(self, ev: Dict[str, Any]) -> None:
        with self.lock:
            if self.events is None:
                self.events = collections.deque(
                    maxlen=max(16, flags.get("RTPU_EVENTS_BUF")))
            self.events.append(ev)
        self._ensure_flusher()

    def _ensure_flusher(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._run, name="rtpu-events-flush", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            time.sleep(flags.get("RTPU_EVENTS_FLUSH_S"))
            try:
                self.flush()
            except Exception:
                pass  # the event feed must never take a process down

    def flush(self, timeout: float = 30.0) -> bool:
        from . import context as ctx

        with self.lock:
            events = list(self.events) if self.events else []
            if self.events is not None:
                self.events.clear()
        if not events:
            return True
        if not ctx.is_initialized():
            self._requeue(events)
            return False
        try:
            wc = ctx.get_worker_context()
            wc.client.request({"kind": "cluster_events", "events": events},
                              timeout=timeout)
            return True
        except Exception:
            self._requeue(events)
            return False

    def _requeue(self, events: List[Dict[str, Any]]) -> None:
        with self.lock:
            if self.events is None:
                self.events = collections.deque(
                    maxlen=max(16, flags.get("RTPU_EVENTS_BUF")))
            self.events.extendleft(reversed(events))


_shipper = _Shipper()


def emit(severity: str, kind: str, message: str, source: str = "worker",
         **entities: Any) -> None:
    """Buffer one cluster event for shipping to the controller (worker /
    driver processes; the controller emits into its EventLog directly,
    host agents ship theirs on the heartbeat path)."""
    if not enabled():
        return
    _shipper.emit(make_event(severity, source, kind, message, **entities))


def flush_events(timeout: float = 30.0) -> bool:
    """Force a flush (tests / shutdown hooks)."""
    return _shipper.flush(timeout=timeout)
