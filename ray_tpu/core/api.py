"""Public core API: init/shutdown, @remote tasks, actors, get/put/wait.

Parity surface with the reference's L2 API (ray: python/ray/_private/worker.py
init:1214 get:2523 put:2655 wait:2720, remote_function.py:266, actor.py:566),
implemented over the asyncio controller instead of a C++ CoreWorker. See
SURVEY.md §2.1 mapping note for why the Python control plane is acceptable on
TPU: per-step data movement belongs to XLA programs, not to this layer.
"""
from __future__ import annotations

from ray_tpu import flags

import atexit
import functools
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import cloudpickle

from . import context as ctx
from . import ownership
from ..util import tracing
from .client import CoreClient, EventLoopThread
from .controller import Controller, GetTimeoutError, TaskError
from .ids import ActorID, NodeID, ObjectID, TaskID
from .object_store import get_bytes, get_bytes_with_refresh, put_bytes
from .serialization import ObjectRef, pack_args

_init_lock = threading.RLock()
_owned_controller: Optional[Controller] = None
_controller_io: Optional[EventLoopThread] = None


# ------------------------------------------------------------------ lifecycle


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    namespace: str = "default",
    ignore_reinit_error: bool = False,
    runtime_env: Optional[Dict[str, Any]] = None,
) -> "ClusterHandle":
    """Start (or connect to) a cluster and bind this process as the driver.

    With no ``address`` a local controller is started in-process and one
    virtual node is registered with the host's resources (reference:
    ray.init starting GCS+raylet, _private/node.py:1342).
    """
    global _owned_controller, _controller_io
    with _init_lock:
        if ctx.is_initialized():
            if ignore_reinit_error:
                return ClusterHandle(ctx.get_worker_context())
            raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True")

        if address is None:
            # Job entrypoints / `rtpu` CLI processes inherit the cluster
            # address via env (reference: RAY_ADDRESS).
            address = flags.get("RTPU_ADDRESS") or None

        owned = False
        if address is None:
            owned = True
            io = EventLoopThread(name="rtpu-controller")
            controller = Controller()
            host, port = io.call(controller.start(), timeout=10)
            node_res: Dict[str, float] = {
                "CPU": float(num_cpus if num_cpus is not None else os.cpu_count() or 1),
            }
            # Vendor-agnostic autodetection over the registered accelerator
            # managers (util/accelerators.py plugin layer); on a TPU host
            # this adds {"TPU": chips} plus the pod-scoped custom resources
            # when GCE metadata env is present. An explicit num_tpus
            # overrides the detected chip count but must NOT silence the
            # pod resources — the pod-leader scheduling scheme has to work
            # whether or not the user pinned the count.
            from ray_tpu.util.accelerators import (
                detect_node_accelerator_resources,
            )

            node_res.update(detect_node_accelerator_resources())
            if num_tpus is not None:
                node_res.pop("TPU", None)
                if num_tpus:
                    node_res["TPU"] = float(num_tpus)
                    # Detection may have found 0 chips (container without
                    # /dev/accel*) and thus skipped the TPU manager's
                    # additional resources — an explicit chip count says
                    # this IS a TPU host, so advertise them.
                    from ray_tpu.util.accelerators import (
                        TPUAcceleratorManager,
                    )

                    try:
                        for k, v in \
                                TPUAcceleratorManager.additional_resources() \
                                .items():
                            node_res.setdefault(k, v)
                    except Exception:
                        pass
            if resources:
                node_res.update(resources)
            # ensure_head_node: a state-path restore brings back the prior
            # head node — reuse its identity instead of adding a duplicate.
            node_id = controller.ensure_head_node(node_res,
                                                  labels={"head": "1"})
            _owned_controller = controller
            _controller_io = io
            address = f"{host}:{port}"
        else:
            node_id = ""

        host, port_s = address.rsplit(":", 1)
        # Drivers of a REMOTE controller survive a controller bounce: the
        # client reconnects with capped backoff, re-registers, and resubmits
        # in-flight plain tasks (an embedded controller dies with this
        # process, so reconnect would only mask real shutdown races).
        client = CoreClient(host, int(port_s), handler=_driver_handler,
                            reconnect=not owned,
                            on_reconnect=_driver_on_reconnect)
        reg = client.request({"kind": "register", "role": "driver"})
        # A driver on a host with no pull server (neither the controller's
        # host nor an agent's) cannot serve its shm objects to workers: its
        # puts must travel inline on the control plane.
        from .object_store import current_host_id

        ctrl_host = (reg or {}).get("controller_host_id")
        if ctrl_host is not None and ctrl_host != current_host_id():
            flags.set_env("RTPU_FORCE_INLINE", "1")
        if not node_id:
            state = client.request({"kind": "cluster_state"})
            node_id = state["nodes"][0]["node_id"] if state["nodes"] else ""
        wc = ctx.WorkerContext(client=client, node_id=node_id, role="driver", namespace=namespace)
        wc.extra["address"] = address
        if runtime_env:
            # Job-level default env (reference: ray.init(runtime_env=...));
            # applied to every task/actor unless overridden per-call.
            wc.extra["default_runtime_env"] = dict(runtime_env)
        ctx.set_worker_context(wc)
        atexit.register(_atexit_shutdown)
        return ClusterHandle(wc)


async def _driver_handler(conn, msg):
    kind = msg.get("kind")
    if kind == "pubsub":
        ctx.deliver_pubsub(msg["channel"], msg["data"])
    elif kind == "pubsub_batch":
        for item in msg["items"]:
            ctx.deliver_pubsub(item["channel"], item["data"])
    elif kind == "lease_reclaim":
        # The controller has queued work it cannot place while we hold
        # task leases: release every named lease with no in-flight pushes.
        ids = set(msg.get("lease_ids") or ())
        threading.Thread(target=_reclaim_leases, args=(ids,),
                         daemon=True, name="lease-reclaim").start()
    elif kind == "log":
        # A worker's stdout/stderr line, prefixed like the reference's
        # driver-side log tailing ("(pid=...) ...").
        import sys

        stream = sys.stderr if msg.get("stream") == "stderr" else sys.stdout
        try:
            stream.write(f"(worker pid={msg.get('pid')}) {msg['line']}\n")
            stream.flush()
        except Exception:
            pass
    return None


def _driver_on_reconnect(client: CoreClient) -> None:
    """Runs on the fresh connection after a controller bounce, before any
    retried request goes out: re-register as a driver, drop task-lease
    pools the restarted controller knows nothing about, and resubmit
    in-flight plain tasks so blocked get()s complete without a driver
    restart (at-least-once for retryable work; actor routes stay — live
    actor workers keep serving direct calls through the bounce)."""
    # Bounded handshake when the partition-hardening RPC timeout is on: a
    # re-dial into a still-blackholed network must fail fast and keep
    # retrying from ensure_connected, not camp on a 30s wait.
    _t = float(flags.get("RTPU_RPC_TIMEOUT_S") or 0.0)
    client.io.call(
        client.conn.request({"kind": "register", "role": "driver"},
                            timeout=_t * 2 if _t else None),
        timeout=(_t * 2 if _t else 30) + 5)
    # Rotate the client token: per-session caches keyed on it (function
    # registrations, actor routes) re-validate against the restarted
    # controller instead of trusting state it may not have. (Functions of
    # ALREADY in-flight specs come from the --state-path function table.)
    import secrets

    client.token = secrets.token_hex(8)
    # The restarted controller has no lease ledger: forget leased routes so
    # fresh leases are negotiated (the workers themselves re-register as
    # idle). Idle routes close now; routes with pushes IN FLIGHT are
    # retired instead — the hosting workers survive the bounce, so their
    # batches complete on the live direct connections (results publish to
    # the restarted controller once the workers re-register) and the done
    # callback closes each drained route. Closing them here would turn a
    # controller bounce into spurious WorkerCrashedErrors on retry-less
    # directly-pushed tasks.
    for pool in list(_task_pools.values()):
        with pool.lock:
            routes, pool.routes = pool.routes, []
            busy = [r for r in routes if r.inflight > 0]
            for r in busy:
                r.retired = True
        for r in routes:
            if r.inflight > 0:
                continue
            try:
                client.io.call_nowait(r.conn.close())
            except Exception:
                pass
    _task_pools.clear()
    with _inflight_lock:
        specs = [dict(s) for s in _inflight_specs.values()]
    for spec in specs:
        # A spec whose direct push is still in flight on a surviving route
        # must NOT be resubmitted — the live worker will run it; a
        # duplicate through the queue would double-execute it.
        if any(oid in _inflight_direct
               for oid in (spec.get("return_ids") or ())):
            continue
        # Stale placement/dispatch residue must not ride the resubmit.
        for k in ("loc_hints", "sched_node", "blocked", "state"):
            spec.pop(k, None)
        try:
            client.io.call(
                client.conn.request({"kind": "submit_task", "spec": spec}),
                timeout=30)
        except Exception:
            pass


# In-flight plain-task specs for controller-bounce resubmission: task_id ->
# spec, retired when any return location is observed (get()/direct reply),
# bounded so fire-and-forget callers can't grow it without limit.
from collections import OrderedDict as _OrderedDict

_inflight_lock = threading.Lock()
_INFLIGHT_MAX = 4096
_inflight_specs: "_OrderedDict[str, Dict[str, Any]]" = _OrderedDict()
_inflight_oid2task: Dict[str, str] = {}


def _track_inflight(spec: Dict[str, Any]) -> None:
    if spec.get("actor_id") or spec.get("is_actor_creation") \
            or spec.get("streaming") or not spec.get("return_ids"):
        return
    with _inflight_lock:
        _inflight_specs[spec["task_id"]] = spec
        for oid in spec["return_ids"]:
            _inflight_oid2task[oid] = spec["task_id"]
        while len(_inflight_specs) > _INFLIGHT_MAX:
            _, old = _inflight_specs.popitem(last=False)
            for oid in old.get("return_ids") or ():
                _inflight_oid2task.pop(oid, None)


def _untrack_inflight(object_id: str) -> None:
    if object_id not in _inflight_oid2task:
        return
    with _inflight_lock:
        tid = _inflight_oid2task.pop(object_id, None)
        spec = _inflight_specs.pop(tid, None) if tid else None
        if spec:
            for oid in spec.get("return_ids") or ():
                _inflight_oid2task.pop(oid, None)


def _untrack_inflight_many(object_ids) -> None:
    hits = [oid for oid in object_ids if oid in _inflight_oid2task]
    if not hits:
        return
    with _inflight_lock:
        for object_id in hits:
            tid = _inflight_oid2task.pop(object_id, None)
            spec = _inflight_specs.pop(tid, None) if tid else None
            if spec:
                for oid in spec.get("return_ids") or ():
                    _inflight_oid2task.pop(oid, None)


def _atexit_shutdown() -> None:
    try:
        shutdown()
    except Exception:
        pass


def shutdown() -> None:
    global _owned_controller, _controller_io
    with _init_lock:
        if not ctx.is_initialized():
            return
        wc = ctx.get_worker_context()
        ownership.shutdown()
        _reset_direct_state(wc)
        if _owned_controller is not None and _controller_io is not None:
            try:
                _controller_io.call(_owned_controller.shutdown(), timeout=5)
            except Exception:
                pass
        try:
            wc.client.close()
        except Exception:
            pass
        if _controller_io is not None:
            _controller_io.stop()
        _owned_controller = None
        _controller_io = None
        ctx.set_worker_context(None)
        flags.unset_env("RTPU_FORCE_INLINE")
        from .object_store import close_process_segments
        from .transfer import reset_transfer_caches

        close_process_segments()
        reset_transfer_caches()


def is_initialized() -> bool:
    return ctx.is_initialized()


@dataclass
class ClusterHandle:
    wc: ctx.WorkerContext

    @property
    def address(self) -> str:
        return self.wc.extra.get("address", "")


# ------------------------------------------------------------------- get/put


def put(value: Any) -> ObjectRef:
    wc = ctx.get_worker_context()
    oid = ObjectID.generate()
    loc = put_bytes(value, oid, wc.node_id)
    # The producer knows the location — cache it so get() of own puts never
    # asks the controller; the directory registration is pipelined (same
    # connection, so any subsequent submit referencing this ref is ordered
    # after it, and remote consumers block in get_locations until it lands).
    _cache_loc(loc)
    _pipelined_submit(wc, {"kind": "put_location", "loc": loc}, (oid,))
    ownership.claim_ownership(oid, loc)
    return ObjectRef(oid, ownership.self_addr())


def _with_block_notify(fn: Callable[[], Any]) -> Any:
    """Release this task's CPU while blocked in get/wait (reference:
    NotifyDirectCallTaskBlocked, src/ray/raylet_client/raylet_client.h:380)."""
    wc = ctx.get_worker_context()
    task_id = ctx.current_task_id()
    if task_id is None or wc.role != "worker":
        return fn()
    wc.client.request({"kind": "task_blocked", "task_id": task_id})
    try:
        return fn()
    finally:
        try:
            wc.client.request({"kind": "task_unblocked", "task_id": task_id})
        except Exception:
            pass


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None) -> Any:
    wc = ctx.get_worker_context()
    single = isinstance(refs, ObjectRef)
    ref_list: List[ObjectRef] = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
    ids = [r.object_id for r in ref_list]
    # Direct-call results are cached locally — only unknown ids hit the
    # controller (and skip the blocked-notify round trips entirely when
    # everything is local). In-flight direct replies are awaited here
    # rather than asking the controller for locations that are already on
    # the wire. The controller deadline is reduced by the time spent
    # waiting so the overall budget stays `timeout`.
    t_start = time.monotonic()
    if _inflight_direct:
        _await_inflight(ids, timeout)
    missing = [oid for oid in ids if oid not in _local_locs]
    remaining_timeout = (None if timeout is None else
                         max(0.0, timeout - (time.monotonic() - t_start)))

    owners = {r.object_id: r.owner for r in ref_list
              if r.owner and r.object_id in missing}

    def fetch():
        # node_id: the controller resolves replica-aware (consumer-local
        # copies of broadcast objects beat cross-host pulls).
        return wc.client.request(
            {"kind": "get_locations", "object_ids": missing,
             "timeout": remaining_timeout, "owners": owners,
             "node_id": wc.node_id}
        )

    locs = _with_block_notify(fetch) if missing else {}
    for loc in locs.values():
        # Cache controller-fetched locations: later submits that depend on
        # these objects stay eligible for direct dispatch (the lease path
        # requires locally-known dep locations), and repeat gets skip the
        # directory. get_bytes_with_refresh re-resolves stale entries.
        # (_cache_loc also releases this process's submit holds for
        # observed task returns — the single load-bearing hook.)
        _cache_loc(loc)
    out = []
    for oid in ids:
        loc = locs.get(oid) or _local_locs.get(oid)
        if loc is None:
            # Cached entry evicted/freed between the missing-computation
            # and here (LRU bound or concurrent free): the controller is
            # the authority.
            loc = wc.client.request(
                {"kind": "get_locations", "object_ids": [oid],
                 "timeout": remaining_timeout, "node_id": wc.node_id})[oid]
        val, loc = get_bytes_with_refresh(loc, oid, wc.client.request)
        if loc.is_error:
            if isinstance(val, BaseException):
                raise val
            raise RuntimeError(str(val))
        out.append(val)
    return out[0] if single else out


def broadcast(ref: ObjectRef, node_ids: Optional[Sequence[str]] = None,
              *, timeout: float = 120.0) -> Dict[str, Any]:
    """Replicate one object's bytes onto N nodes in a single pass.

    The bytes move source -> N over a pipelined chain of hosts (each hop
    stores a full local copy while forwarding downstream), so the producer
    ships each byte ~once regardless of fan-out — the weight-distribution
    primitive for async-RL topologies (reference: Ray's object-manager
    Push + ray.experimental.channel broadcast). Afterwards, ``get()`` (and
    task argument resolution) on a target node reads the local replica
    over shared memory.

    ``node_ids=None`` targets every alive node that doesn't already hold
    the bytes. Returns ``{ok, replicas: {node_id: "ok"}, skipped: {...},
    stats: {source_bytes}, rounds}``; nodes that die or drain mid-flight
    are re-routed onto a fresh chain and reported in ``skipped``.
    """
    if not isinstance(ref, ObjectRef):
        raise TypeError(f"broadcast() expects an ObjectRef, got {type(ref)}")
    wc = ctx.get_worker_context()
    return wc.client.request(
        {"kind": "broadcast_object", "object_id": ref.object_id,
         "node_ids": list(node_ids) if node_ids is not None else None,
         "timeout": timeout},
        timeout=timeout + 10)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    wc = ctx.get_worker_context()
    ids = [r.object_id for r in refs]
    if num_returns > len(ids):
        raise ValueError("num_returns exceeds number of refs")
    local_ready = [oid for oid in ids if oid in _local_locs]
    if len(local_ready) >= num_returns:
        ready_ids = set(local_ready[:num_returns])
        ready = [r for r in refs if r.object_id in ready_ids]
        return ready, [r for r in refs if r.object_id not in ready_ids]

    def do():
        return wc.client.request(
            {"kind": "wait", "object_ids": ids, "num_returns": num_returns, "timeout": timeout}
        )

    ready_ids = set(_with_block_notify(do))
    ready = [r for r in refs if r.object_id in ready_ids]
    not_ready = [r for r in refs if r.object_id not in ready_ids]
    return ready, not_ready


def error_of(ref: ObjectRef, *,
             timeout: Optional[float] = 30.0) -> Optional[BaseException]:
    """The exception a READY object holds, or None for a data object.

    A location-metadata probe, not a fetch: callers that stream large
    blocks by reference (the data plane's executor) use this to classify
    a completed task/actor-call ref as success vs typed system failure
    (ActorDiedError / WorkerCrashedError / NodePreemptedError / ...)
    without ever pulling the payload bytes of a healthy block to this
    process. Direct-dispatch results answer from the local location
    cache (one dict lookup); otherwise one get_locations round trip.
    Only error payloads — which are small — are materialized."""
    wc = ctx.get_worker_context()
    oid = ref.object_id
    loc = _local_locs.get(oid)
    if loc is None:
        locs = wc.client.request(
            {"kind": "get_locations", "object_ids": [oid],
             "timeout": timeout, "node_id": wc.node_id})
        loc = locs[oid]
        _cache_loc(loc)
    if not loc.is_error:
        return None
    val, _ = get_bytes_with_refresh(loc, oid, wc.client.request)
    if isinstance(val, BaseException):
        return val
    return RuntimeError(str(val))


def free(refs: Sequence[ObjectRef]) -> None:
    wc = ctx.get_worker_context()
    for r in refs:
        _local_locs.pop(r.object_id, None)
    wc.client.request({"kind": "free_objects", "object_ids": [r.object_id for r in refs]})


# ------------------------------------------------------------------- tasks


def _validate_accel_quantity(resource: str, quantity: Any) -> float:
    """Validate an accelerator request against its registered manager
    (reference: option validation via accelerator.validate_resource_request_
    quantity in _private/ray_option_utils.py)."""
    from ray_tpu.util.accelerators import manager_for_resource

    mgr = manager_for_resource(resource)
    if mgr is not None:
        ok, err = mgr.validate_request(float(quantity))
        if not ok:
            raise ValueError(err)
    return float(quantity)


def _validate_accel_resources(resources: Dict[str, float]) -> Dict[str, float]:
    """Validate every accelerator-managed entry of a resources dict — the
    resources={"TPU": n} spelling must hit the same validation as
    num_tpus=n."""
    for name, q in resources.items():
        _validate_accel_quantity(name, q)
    return resources


def _normalize_strategy(scheduling_strategy: Any) -> Tuple[Dict[str, Any], Optional[Tuple[str, int]]]:
    """Returns (strategy dict, pg tuple)."""
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        NodeLabelSchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    if scheduling_strategy is None or scheduling_strategy == "DEFAULT":
        return {"type": "DEFAULT"}, None
    if scheduling_strategy == "SPREAD":
        return {"type": "SPREAD"}, None
    if isinstance(scheduling_strategy, NodeAffinitySchedulingStrategy):
        return (
            {"type": "NODE_AFFINITY", "node_id": scheduling_strategy.node_id,
             "soft": scheduling_strategy.soft},
            None,
        )
    if isinstance(scheduling_strategy, NodeLabelSchedulingStrategy):
        return {"type": "NODE_LABEL", "labels": scheduling_strategy.hard}, None
    if isinstance(scheduling_strategy, PlacementGroupSchedulingStrategy):
        pg = scheduling_strategy.placement_group
        idx = scheduling_strategy.placement_group_bundle_index
        if idx is None or idx < 0:
            idx = -1  # reference semantics: any bundle in the group
        return {"type": "DEFAULT"}, (pg.id, idx)
    raise ValueError(f"unknown scheduling strategy {scheduling_strategy!r}")


class ObjectRefGenerator:
    """Iterator over the refs of a streaming task's yields (reference:
    StreamingObjectRefGenerator, python/ray/_raylet.pyx:273). Each __next__
    blocks until the producer reports the item — the consumer can hold item
    0 while the producer is still running."""

    def __init__(self, task_id: str):
        self._task_id = task_id
        self._index = 0
        self._exhausted = False

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        if self._exhausted:
            # The controller pops generator state at exhaustion; re-asking
            # for the task would error instead of honoring the protocol.
            raise StopIteration
        wc = ctx.get_worker_context()
        r = wc.client.request(
            {"kind": "generator_next", "task_id": self._task_id, "index": self._index}
        )
        if r.get("done"):
            self._exhausted = True
            raise StopIteration
        self._index += 1
        return ObjectRef(r["object_id"])

    def close(self) -> None:
        """Tell the controller this consumer is gone so a producer stalled
        in the backpressure window is released and state is reclaimed.

        MUST be fire-and-forget: __del__ can run on any thread during GC —
        including an event-loop thread — where a blocking request deadlocks
        the loop against itself (observed: GC inside a controller handler
        collecting a stale generator wedged the whole control plane)."""
        if self._exhausted:
            return
        self._exhausted = True
        try:
            wc = ctx.get_worker_context()
            wc.client.request_async(
                {"kind": "generator_close", "task_id": self._task_id}
            )
        except Exception:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __reduce__(self):
        # Pickling hands ownership to the receiver: disarm close-on-del in
        # this copy so its destruction doesn't cancel the remote consumer.
        self._exhausted = True
        return (ObjectRefGenerator, (self._task_id,))


def _streaming_spec_opts(opts: Dict[str, Any], spec: Dict[str, Any]) -> None:
    spec["streaming"] = True
    spec["backpressure"] = int(
        opts.get("_generator_backpressure_num_objects", 16) or 16
    )


def _attach_runtime_env(wc: ctx.WorkerContext, opts: Dict[str, Any],
                        spec: Dict[str, Any]) -> None:
    """Resolve the effective runtime env (call option > job default) into
    the spec. Normalization (zip + KV upload) is cached per raw-env content
    so repeated calls don't re-zip."""
    raw = opts.get("runtime_env") or wc.extra.get("default_runtime_env")
    if not raw:
        return
    import json as _json

    from . import runtime_env as renv

    cache = wc.extra.setdefault("_renv_cache", {})
    key = _json.dumps(raw, sort_keys=True, default=str)
    if raw.get("working_dir"):
        # Editing files between submissions must ship the new content: key
        # the cache by a cheap directory fingerprint, not the path string.
        key += "|" + renv.working_dir_fingerprint(raw["working_dir"])
    norm = cache.get(key)
    if norm is None:
        norm = renv.normalize(raw, wc.client)
        cache[key] = norm
    if norm:
        spec["runtime_env"] = norm
        spec["env_hash"] = norm["hash"]


class RemoteFunction:
    """Handle produced by @remote on a function (reference:
    python/ray/remote_function.py:266 RemoteFunction._remote)."""

    def __init__(self, fn: Callable, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._options = options or {}
        self._func_id: Optional[str] = None
        self._registered_with: Optional[str] = None
        # Amortized submission: the spec's static fields (closure id,
        # validated resources, normalized strategy, retry options) are
        # computed once per session and shared by every call's spec — each
        # .remote() builds only its ids and args, and batched pushes pickle
        # the shared sub-objects once per frame (pickle memo), not per call.
        self._tmpl: Optional[Tuple[Dict[str, Any], bool, Any]] = None
        self._tmpl_key: Optional[str] = None
        functools.update_wrapper(self, fn)

    def options(self, **opts) -> "RemoteFunction":
        new = RemoteFunction(self._fn, {**self._options, **opts})
        new._func_id = self._func_id
        new._registered_with = self._registered_with
        return new

    def _ensure_registered(self, wc: ctx.WorkerContext) -> str:
        key = wc.client.token
        if self._func_id is None or self._registered_with != key:
            # Assign the id BEFORE pickling: if the function's closure
            # references this handle (recursive remote fn / workflow
            # continuation), the nested __reduce__ must see a settled id
            # instead of re-entering registration forever.
            func_id = TaskID.generate()
            self._func_id = func_id
            self._registered_with = key
            try:
                blob = cloudpickle.dumps(self._fn)
                wc.client.request({"kind": "register_function",
                                   "func_id": func_id, "blob": blob})
            except BaseException:
                self._func_id = None
                self._registered_with = None
                raise
        return self._func_id

    def _ensure_template(self, wc: ctx.WorkerContext):
        key = wc.client.token
        if self._tmpl is not None and self._tmpl_key == key:
            return self._tmpl
        func_id = self._ensure_registered(wc)
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        resources = dict(opts.get("resources", {}) or {})
        resources["CPU"] = float(opts.get("num_cpus", 1 if "num_tpus" not in opts else 0))
        if opts.get("num_tpus"):
            resources["TPU"] = float(opts["num_tpus"])
        _validate_accel_resources(resources)
        strategy, pg = _normalize_strategy(opts.get("scheduling_strategy"))
        tmpl = {
            "func_id": func_id,
            "resources": {k: v for k, v in resources.items() if v},
            "scheduling": strategy,
            "pg": pg,
            "label": getattr(self._fn, "__name__", "task"),
            "max_retries": int(opts.get("max_retries", 0)),
            # True retries APPLICATION errors too (reference
            # retry_exceptions; bool form — per-exception-class lists are
            # not supported).
            "retry_exceptions": bool(opts.get("retry_exceptions", False)),
        }
        self._tmpl = (tmpl, streaming, num_returns)
        self._tmpl_key = key
        return self._tmpl

    def remote(self, *args, **kwargs):
        wc = ctx.get_worker_context()
        tmpl, streaming, num_returns = self._ensure_template(wc)
        opts = self._options
        args_blob, deps, nested_refs = pack_args(args, kwargs)
        n_rets = 0 if streaming else max(num_returns, 0)
        return_ids = [ObjectID.generate() for _ in range(n_rets)]
        # Static fields come as shared references from the template; only
        # ids and args are per-call.
        spec = dict(tmpl)
        spec["task_id"] = TaskID.generate()
        spec["args_blob"] = args_blob
        spec["deps"] = deps
        spec["return_ids"] = return_ids
        if opts.get("deadline_s") is not None:
            # Absolute end-to-end deadline: every queue boundary (scheduler
            # pop, worker dequeue) drops the spec once it passes.
            spec["deadline_ts"] = time.time() + float(opts["deadline_s"])
        ptid = ctx.current_task_id()
        if ptid:
            # Ownership edge for rtpu.cancel(recursive=True).
            spec["parent_task_id"] = ptid
        _attach_runtime_env(wc, opts, spec)
        if streaming:
            _streaming_spec_opts(opts, spec)
        if deps or nested_refs:
            _register_dep_holds(spec, nested_refs)
        tracing.inject_submit_span(spec, spec["label"])
        if flags.get("RTPU_TASK_EVENTS"):
            # Flight-recorder anchor: the executing worker derives
            # scheduling delay (submit -> dispatch arrival) from this.
            spec["submit_ts"] = time.time()
        # Lease-then-push direct path first; the controller queue is the
        # fallback (and the only path for pg/affinity/streaming tasks).
        # Only controller-path specs enter the bounce-resubmission buffer:
        # a direct push has its own recovery (the batch done callback),
        # and a bounce must not double-schedule work a live worker still
        # holds.
        if not _try_direct_task(wc, spec, opts):
            _track_inflight(spec)
            _pipelined_submit(wc, {"kind": "submit_task", "spec": spec},
                              spec["return_ids"])
        elif "parent_task_id" in spec:
            # Direct push: the controller never sees the submission, so the
            # ownership edge for recursive cancel ships as a fire-and-forget
            # note (only paid when running INSIDE a task — driver submits
            # carry no parent and skip this entirely).
            _note_task_lineage(wc, spec)
        if streaming:
            return ObjectRefGenerator(spec["task_id"])
        refs = _claim_return_refs(return_ids)
        if num_returns == 1:
            return refs[0]
        if num_returns == 0:
            return None
        return refs

    def bind(self, *args, **kwargs):
        """Author a lazy DAG node instead of submitting (reference
        python/ray/dag/function_node.py; used by ray_tpu.workflow and
        ray_tpu.dag.compiled_dag)."""
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __reduce__(self):
        # RemoteFunction handles travel inside task results (workflow
        # continuations return DAG nodes holding one). Pickling the wrapped
        # fn by value recurses when its closure references the handle itself
        # (e.g. a recursive continuation), so ship it *by function-table id*
        # — the blob is already exported via register_function. Without a
        # live session (plain copy.deepcopy of a config holding a handle)
        # fall back to by-value, the pre-session behavior.
        if not is_initialized():
            # Re-entrancy guard mirroring the session path: a recursive
            # handle (fn's closure → this object) would otherwise nest
            # cloudpickle.dumps forever. First entry dumps the fn under a
            # token; nested entries reduce to a by-token backreference that
            # the (equally nested) load resolves to the same object.
            state = _value_pickle_state()
            token = state["dumping"].get(id(self))
            if token is not None:
                return (_rebuild_value_backref, (token,))
            token = f"rf-{id(self):x}-{len(state['dumping'])}"
            state["dumping"][id(self)] = token
            try:
                blob = cloudpickle.dumps(self._fn)
            finally:
                del state["dumping"][id(self)]
            return (_rebuild_remote_function_value,
                    (token, blob, self._options))
        wc = ctx.get_worker_context()
        func_id = self._ensure_registered(wc)
        return (_rebuild_remote_function, (func_id, self._options))

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self.__name__!r} cannot be called directly; "
            f"use .remote() or access the underlying function via ._fn"
        )


# Rebuild bookkeeping for by-table-id function handles. ``_rebuilding`` is
# keyed per-thread: a function whose closure references its own handle
# re-enters _rebuild_remote_function while its blob loads and must get the
# same placeholder back, but another thread must NOT observe the partially
# initialized object — it performs its own fetch instead. ``_fn_cache``
# memoizes completed loads so repeat deserializations of the same func_id
# (deep workflow continuations) skip the fetch RPC + unpickle.
_rebuilding: Dict[Any, "RemoteFunction"] = {}
_fn_cache: Dict[Any, Callable] = {}


def _rebuild_remote_function(func_id: str, options) -> "RemoteFunction":
    wc = ctx.get_worker_context()
    cache_key = (wc.client.token, func_id)
    local_key = (threading.get_ident(),) + cache_key
    if local_key in _rebuilding:
        return _rebuilding[local_key]
    fn = _fn_cache.get(cache_key)
    if fn is not None:
        rf = RemoteFunction(fn, options)
    else:
        rf = RemoteFunction.__new__(RemoteFunction)
        _rebuilding[local_key] = rf
        try:
            blob = wc.client.request(
                {"kind": "fetch_function", "func_id": func_id})
            rf.__init__(cloudpickle.loads(blob), options)
            _fn_cache[cache_key] = rf._fn
        finally:
            del _rebuilding[local_key]
    rf._func_id = func_id
    rf._registered_with = wc.client.token
    return rf


_value_tl = threading.local()


def _value_pickle_state() -> Dict[str, Dict]:
    if not hasattr(_value_tl, "state"):
        _value_tl.state = {"dumping": {}, "loading": {}}
    return _value_tl.state


def _rebuild_remote_function_value(token: str, fn_blob: bytes,
                                   options) -> "RemoteFunction":
    state = _value_pickle_state()
    rf = RemoteFunction.__new__(RemoteFunction)
    state["loading"][token] = rf
    try:
        rf.__init__(cloudpickle.loads(fn_blob), options)
    finally:
        del state["loading"][token]
    return rf


def _rebuild_value_backref(token: str) -> "RemoteFunction":
    return _value_pickle_state()["loading"][token]


# ------------------------------------------------------------------- actors

# ---- direct dispatch (lease-then-push) -------------------------------------
# Reference: src/ray/core_worker/transport/direct_task_transport.h:222 and
# direct_actor_task_submitter.h:74 — resolve the actor's worker address once
# via the controller, then push calls peer-to-peer. The controller keeps the
# directory/health/GC roles; it is no longer on the per-call path. Result
# locations return inline on the direct reply and are cached process-locally,
# so the subsequent get() usually needs no controller round trip either (the
# executing worker still fire-and-forget-reports task_done so third-party
# consumers and the state API converge).

from collections import OrderedDict

_routes_lock = threading.Lock()
_routes: Dict[Tuple[str, str], "_ActorRoute"] = {}
_local_locs: "OrderedDict[str, Any]" = OrderedDict()
_LOCAL_LOCS_MAX = 65536


class _ActorRoute:
    """Cached direct path to one actor (per client session)."""

    def __init__(self) -> None:
        self.conn = None  # protocol.Connection on the client's io loop
        self.worker_id: Optional[str] = None
        self.lock = threading.Lock()
        self.batcher: Optional["_PushBatcher"] = None


# ---- submit batching --------------------------------------------------------
# Every spec appended during one event-loop beat rides ONE framed
# direct_task_batch / direct_actor_task_batch message (one pickle, one
# syscall) and ONE aggregated reply. Specs built from a shared template
# (RemoteFunction._submit_template) reference the same static sub-objects,
# so pickle's memo serializes the closure/option template once per batch —
# each additional call costs its args and ids on the wire, nothing else.


class _PushBatch:
    __slots__ = ("specs", "fut", "maxn", "settled")

    def __init__(self) -> None:
        import concurrent.futures

        self.specs: List[Dict[str, Any]] = []
        self.fut: "Any" = concurrent.futures.Future()
        # Seal bound, read once at batch open (not one flag read per add).
        self.maxn = flags.get("RTPU_SUBMIT_BATCH_MAX")
        # One settle per batch: the partition-hardening timeout watchdog
        # and the (late) real reply race onto the same io thread; whichever
        # fires first wins, the other is a no-op.
        self.settled = False


class _PushBatcher:
    """Per-connection micro-batcher for direct pushes.

    ``add`` appends a spec to the open batch and (once per batch) schedules
    a flush on the io loop — the flush runs within the same loop beat, so a
    lone call's latency is unchanged while a burst coalesces into one frame.
    The ``on_done(batch, result, exc)`` callback fires once per batch with
    the aggregated reply (or the transport error)."""

    __slots__ = ("kind", "conn", "io", "on_done", "lock", "cur", "closed",
                 "scheduled")

    def __init__(self, kind: str, conn, io, on_done) -> None:
        self.kind = kind
        self.conn = conn
        self.io = io
        self.on_done = on_done
        self.lock = threading.Lock()
        self.cur: Optional[_PushBatch] = None
        self.closed: List[_PushBatch] = []
        self.scheduled = False

    def add(self, spec: Dict[str, Any], return_ids, meta=None) -> Any:
        """Append one spec; registers its return ids in the in-flight maps
        under the batcher lock (so the batch's done callback, which pops
        them, can never run before they are registered). Returns the
        batch's shared future."""
        with self.lock:
            b = self.cur
            if b is None:
                b = self.cur = _PushBatch()
            b.specs.append(spec)
            for oid in return_ids:
                _inflight_direct[oid] = b.fut
                if meta is not None:
                    _direct_task_meta[oid] = meta
            if len(b.specs) >= b.maxn:
                self.closed.append(b)
                self.cur = None
            if self.scheduled:
                return b.fut
            self.scheduled = True
        try:
            self.io.loop.call_soon_threadsafe(self._flush)
        except RuntimeError as e:  # io loop gone (shutdown race)
            self._fail_open_batches(ConnectionError(str(e)))
        return b.fut

    def _settle(self, b: _PushBatch, res, exc) -> None:
        """Run the batch's bookkeeping callback, then resolve the shared
        future (in that order: by the time a waiter in _await_inflight
        wakes, the aggregated locations are cached and the in-flight maps
        are settled)."""
        if b.settled:
            return
        b.settled = True
        try:
            self.on_done(b, res, exc)
        finally:
            if exc is not None:
                if not b.fut.done():
                    b.fut.set_exception(exc)
            elif not b.fut.done():
                b.fut.set_result(res)

    def _fail_open_batches(self, exc: BaseException) -> None:
        with self.lock:
            batches, self.closed = self.closed, []
            if self.cur is not None:
                batches.append(self.cur)
                self.cur = None
            self.scheduled = False
        for b in batches:
            self._settle(b, None, exc)

    def _flush(self) -> None:
        """Runs on the io loop: seal and send every pending batch, in
        append order (FIFO scheduling keeps cross-batch submission order,
        which the actor mailbox's seqno reordering relies on only as a
        fallback)."""
        with self.lock:
            batches, self.closed = self.closed, []
            if self.cur is not None:
                batches.append(self.cur)
                self.cur = None
            self.scheduled = False
        try:
            rpc_t = float(flags.get("RTPU_RPC_TIMEOUT_S") or 0.0)
        except Exception:
            rpc_t = 0.0
        for b in batches:
            try:
                rfut = self.conn.request_threadsafe(
                    {"kind": self.kind, "specs": b.specs})
            except Exception as e:  # noqa: BLE001
                self._settle(b, None, e)
                continue

            def _chain(f, b=b):
                exc = f.exception() if not f.cancelled() else \
                    ConnectionError("request cancelled")
                if exc is not None:
                    self._settle(b, None, exc)
                else:
                    self._settle(b, f.result() or {}, None)

            rfut.add_done_callback(_chain)
            if rpc_t:
                # Partition hardening: a push into a blackholed-but-open
                # connection never answers — after a generous multiple of
                # the RPC timeout, fail the batch into the normal recovery
                # path (replayable actors resubmit safely; plain tasks run
                # the published-vs-unacked probe). 4x the control-plane
                # timeout so genuinely slow calls don't trip it; 0
                # (default) arms nothing.
                def _expire(b=b, rfut=rfut):
                    if not b.settled:
                        self._settle(b, None, ConnectionError(
                            f"direct push unanswered after "
                            f"{rpc_t * 4:.1f}s (suspected partition)"))

                try:
                    self.io.loop.call_later(rpc_t * 4, _expire)
                except RuntimeError:
                    pass


def _cache_loc(loc) -> None:
    _local_locs[loc.object_id] = loc
    while len(_local_locs) > _LOCAL_LOCS_MAX:
        _local_locs.popitem(last=False)
    # A visible location/error for a task return means the spec is no longer
    # in flight — the submitter's dep holds can go (ownership protocol;
    # no-op for oids this process didn't submit), and the spec leaves the
    # controller-bounce resubmission buffer.
    ownership.on_return_location(loc.object_id)
    _untrack_inflight(loc.object_id)


def _cache_locs(locs) -> None:
    """Batch form of _cache_loc for aggregated direct replies: one lock
    round per batch for the ownership release and the in-flight buffer
    instead of one per location (this runs on the io thread — its GIL
    share comes straight out of the submitting thread's budget)."""
    if not locs:
        return
    oids = []
    for loc in locs:
        _local_locs[loc.object_id] = loc
        oids.append(loc.object_id)
    while len(_local_locs) > _LOCAL_LOCS_MAX:
        _local_locs.popitem(last=False)
    ownership.on_return_locations(oids)
    _untrack_inflight_many(oids)


_actor_seqnos: Dict[str, int] = {}
_actor_seqnos_lock = threading.Lock()


def _next_actor_seqno(actor_id: str) -> int:
    with _actor_seqnos_lock:
        n = _actor_seqnos.get(actor_id, 0)
        _actor_seqnos[actor_id] = n + 1
        return n


def _register_dep_holds(spec: Dict[str, Any], nested_refs=()) -> None:
    """Pin the spec's deps AND refs nested in its args at their owners for
    the life of the submission (reference: reference_count.h counts every id
    serialized into a task spec, top-level or nested)."""
    held = list(spec.get("deps") or [])
    for r in nested_refs:
        if r.object_id not in held:
            held.append(r.object_id)
    dep_owners = ownership.register_submit_holds(
        spec["task_id"], held, spec.get("return_ids") or [])
    if dep_owners:
        spec["dep_owners"] = dep_owners


def _claim_return_refs(return_ids) -> List[ObjectRef]:
    """Task returns are owned by the calling process (reference semantics:
    the caller, not the executing worker, owns task results). One locked
    pass claims + counts every id; the handles are built via __new__ so
    __init__ doesn't take the ref lock a second time per id."""
    addr = ownership.claim_return_refs(return_ids)
    refs = []
    for oid in return_ids:
        r = ObjectRef.__new__(ObjectRef)
        r.object_id = oid
        r.owner = addr
        refs.append(r)
    return refs


def _get_route(wc, actor_id: str) -> "_ActorRoute":
    key = (wc.client.token, actor_id)
    with _routes_lock:
        route = _routes.get(key)
        if route is None:
            route = _routes[key] = _ActorRoute()
        return route


def _invalidate_route(wc, route: "_ActorRoute") -> None:
    with route.lock:
        conn, route.conn = route.conn, None
        route.worker_id = None
    if conn is not None:
        try:
            wc.client.io.call_nowait(conn.close())
        except Exception:
            pass


def _resolve_route(wc, route: "_ActorRoute", actor_id: str) -> bool:
    """Resolve + connect the direct path; False -> use the controller path."""
    from . import protocol

    with route.lock:
        if route.conn is not None:
            return True
        try:
            info = wc.client.request(
                {"kind": "resolve_actor", "actor_id": actor_id})
        except Exception:
            return False
        d = info.get("direct")
        if info.get("state") != "alive" or not d:
            return False
        try:
            route.conn = wc.client.io.call(
                protocol.connect(d["host"], d["port"],
                                 name=f"direct->{actor_id[:8]}"),
                timeout=5)
        except Exception:
            route.conn = None
            return False
        route.worker_id = d["worker_id"]
        route.batcher = _PushBatcher(
            "direct_actor_task_batch", route.conn, wc.client.io,
            _make_actor_batch_done(wc, route))
        return True


def _make_actor_batch_done(wc, route: "_ActorRoute"):
    """Done-callback for one actor route's call batches (io thread)."""

    def done(batch: _PushBatch, res, exc) -> None:
        if exc is None:
            if not getattr(batch.fut, "_rtpu_cached", False):
                batch.fut._rtpu_cached = True
                _cache_locs(res.get("locations"))
                _cache_locs(res.get("error_locations"))
            for spec in batch.specs:
                for oid in spec.get("return_ids", ()):
                    _inflight_direct.pop(oid, None)
                    _direct_task_meta.pop(oid, None)
        else:
            for spec in batch.specs:
                for oid in spec.get("return_ids", ()):
                    _inflight_direct.pop(oid, None)
                    _direct_task_meta.pop(oid, None)
            # Runs on the io thread — hand recovery to a plain thread (it
            # issues blocking controller RPCs).
            threading.Thread(
                target=_direct_failure_specs,
                args=(wc, route, list(batch.specs), exc),
                daemon=True, name="direct-recover").start()

    return done


# In-flight direct calls by return id: get() awaits these instead of asking
# the controller for locations the reply will carry any moment.
_inflight_direct: Dict[str, Any] = {}
# return oid -> (task_id, route conn): lets ray_tpu.cancel reach tasks the
# controller never saw (direct lease pushes).
_direct_task_meta: Dict[str, Any] = {}


def _note_task_lineage(wc, spec: Dict[str, Any]) -> None:
    """Ship the parent->child ownership edge for a directly-pushed spec so
    rtpu.cancel(recursive=True) can find it (fire-and-forget; only emitted
    when submitting from INSIDE a task)."""
    try:
        wc.client.send_nowait(
            {"kind": "task_lineage",
             "edges": [(spec["parent_task_id"], spec["task_id"])]})
    except Exception:
        pass


def _direct_submit(wc, route: "_ActorRoute", spec: Dict[str, Any]) -> bool:
    conn = route.conn
    if conn is None:
        return False
    if conn.closed.is_set():
        # Stale route: the actor's old worker died (e.g. its node drained
        # and the actor migrated). Nothing was sent — drop the route and
        # let the caller take the controller path / re-resolve.
        _invalidate_route(wc, route)
        return False
    batcher = route.batcher
    if batcher is not None and flags.get("RTPU_SUBMIT_BATCH"):
        # Batched push: calls appended in one loop beat ride one frame;
        # per-batch bookkeeping lives in _make_actor_batch_done.
        for oid in spec.get("return_ids", ()):
            _direct_task_meta[oid] = (spec["task_id"], conn)
        batcher.add(spec, spec.get("return_ids", ()))
        return True
    try:
        fut = conn.request_threadsafe(
            {"kind": "direct_actor_task", "spec": spec})
    except Exception:
        _invalidate_route(wc, route)
        return False
    for oid in spec.get("return_ids", ()):
        _inflight_direct[oid] = fut
        # Cancel routing: rtpu.cancel(ref) on a direct-pushed actor call
        # rides this same connection straight to the hosting worker — the
        # controller never saw the spec, so it could not help.
        _direct_task_meta[oid] = (spec["task_id"], conn)

    def done(f, wc=wc, route=route, spec=spec):
        for oid in spec.get("return_ids", ()):
            _inflight_direct.pop(oid, None)
            _direct_task_meta.pop(oid, None)
        exc = f.exception()
        if exc is None:
            res = f.result() or {}
            for loc in (res.get("locations") or ()):
                _cache_loc(loc)
            for loc in (res.get("error_locations") or ()):
                _cache_loc(loc)
        else:
            # Runs on the io thread — hand recovery to a plain thread (it
            # issues blocking controller RPCs).
            threading.Thread(
                target=_direct_failure, args=(wc, route, spec, exc),
                daemon=True, name="direct-recover").start()

    fut.add_done_callback(done)
    return True


def _direct_failure(wc, route: "_ActorRoute", spec: Dict[str, Any],
                    exc: BaseException) -> None:
    _direct_failure_specs(wc, route, [spec], exc)


def _direct_failure_specs(wc, route: "_ActorRoute",
                          specs: List[Dict[str, Any]],
                          exc: BaseException) -> None:
    """Direct actor call(s) failed — one push or a whole batch; the same
    decision applies per spec. Resubmit through the controller ONLY when
    the call provably never executed:

    - NeverSentError: the route's connection was already closed at submit —
      the bytes never left this process.
    - ActorNotHostedError: the worker REFUSED the call before any user code
      ran (the actor migrated off a draining node, or died there).
    - A dead connection where the controller says the actor has MOVED off
      the route's worker (drain migration): migration snapshots the
      instance after every queued call completes AND publishes those
      results before the old worker exits, so a call with no published
      results never ran. Results already published mean the call DID
      complete — cache them instead of resubmitting.

    Anything else fails with ActorDiedError — the reference's default
    actor-task semantics: the worker may have executed the call before the
    connection dropped, and silently re-running a non-idempotent method
    would corrupt actor state.

    The error publication is if_absent: the worker's own fire-and-forget
    task_done may have carried real result locations before it died — a
    completed call must stay completed for third-party consumers.
    """
    from . import protocol
    from .controller import ActorNotHostedError

    old_worker = route.worker_id
    _invalidate_route(wc, route)
    resubmit = isinstance(exc, (protocol.NeverSentError, ActorNotHostedError))
    if not resubmit and specs and specs[0].get("replay"):
        # Exactly-once replay actor (max_task_retries): resubmission needs
        # no never-ran proof — calls that DID execute short-circuit on the
        # restored journal, so re-sending can never double-apply them.
        resubmit = True
    done_ids: set = set()
    moved = False
    if not resubmit and isinstance(exc, (ConnectionError, OSError, EOFError)):
        try:
            info = wc.client.request(
                {"kind": "resolve_actor", "actor_id": specs[0]["actor_id"]})
        except Exception:
            info = None
        d = (info or {}).get("direct") or {}
        moved = info is not None and (
            info.get("state") in ("pending", "restarting")
            or (info.get("state") == "alive"
                and d.get("worker_id") not in (None, old_worker)))
        if moved:
            # Which calls completed before the worker left? Migration
            # publishes completed results before the old worker exits, so
            # one wait probe splits the batch: published ⇒ completed
            # (cache, never re-run), unpublished ⇒ never ran (resubmit).
            all_ids = [oid for s in specs
                       for oid in (s.get("return_ids") or ())]
            try:
                ready = wc.client.request(
                    {"kind": "wait", "object_ids": all_ids,
                     "num_returns": len(all_ids), "timeout": 0})
                done_ids = set(ready or ())
            except Exception:
                done_ids = set()
            if done_ids:
                try:
                    locs = wc.client.request(
                        {"kind": "get_locations",
                         "object_ids": sorted(done_ids), "timeout": 1})
                    for loc in locs.values():
                        _cache_loc(loc)
                except Exception:
                    pass
            resubmit = True
    for spec in specs:
        rids = spec.get("return_ids") or ()
        if moved and (not rids
                      or all(oid in done_ids for oid in rids)):
            continue  # the call completed before the worker left
        _finish_failed_actor_call(wc, spec, exc, resubmit)


def _finish_failed_actor_call(wc, spec: Dict[str, Any], exc: BaseException,
                              resubmit: bool) -> None:
    import pickle as _p

    from .controller import ActorDiedError
    from .object_store import ObjectLocation

    if resubmit:
        try:
            wc.client.request({"kind": "submit_actor_task", "spec": spec})
            return
        except Exception:
            pass  # controller unreachable too: fail the call below
    err = ActorDiedError(
        f"actor {spec['actor_id'][:8]} died during a direct call "
        f"({type(exc).__name__}: {exc})")
    data = _p.dumps(err)
    for oid in spec.get("return_ids", ()):
        loc = ObjectLocation(object_id=oid, size=len(data), inline=data,
                             is_error=True)
        if oid not in _local_locs:
            _cache_loc(loc)
        try:
            wc.client.request(
                {"kind": "put_location", "loc": loc, "if_absent": True})
        except Exception:
            pass


def _reset_direct_state(wc=None) -> None:
    if wc is not None:
        for route in list(_routes.values()):
            _invalidate_route(wc, route)  # closes the direct sockets
        for pool in list(_task_pools.values()):
            pool.shutdown(wc)
    _routes.clear()
    _task_pools.clear()
    _local_locs.clear()
    _inflight_direct.clear()
    _direct_task_meta.clear()
    with _inflight_lock:
        _inflight_specs.clear()
        _inflight_oid2task.clear()


# ---- task leases (direct stateless-task dispatch) --------------------------
# Reference: direct_task_transport.h:75 — the owner leases a worker from the
# raylet and pushes tasks to it peer-to-peer; the lease pins the worker's
# resources. The pool below keeps up to _LEASE_MAX leased workers per
# (resources, env) signature, grows while every route is saturated, and
# releases leases that sit idle. Streaming / placement-group / affinity
# tasks stay on the controller path.

_LEASE_PIPELINE = 1         # grow the pool when every route is busy
_LEASE_IDLE_S = 2.0         # release a lease unused this long
_LEASE_BACKOFF_S = 0.5      # after an EMPTY grant, don't retry sooner
_LEASE_GROW_THROTTLE_S = 0.1  # min spacing between growth RPCs otherwise


def _reclaim_leases(lease_ids) -> None:
    """Release every idle route whose lease the controller asked back."""
    try:
        wc = ctx.get_worker_context()
    except Exception:
        return
    for pool in list(_task_pools.values()):
        with pool.lock:
            victims = [r for r in pool.routes
                       if r.lease_id in lease_ids and r.inflight == 0]
            # Out of the pool BEFORE releasing, or a concurrent pick() can
            # hand a mid-release route to a new submit (double-booked
            # worker + spurious WorkerCrashedError on a retry-less task).
            pool.routes = [r for r in pool.routes if r not in victims]
        if victims:
            pool._release_many(wc, victims)


class _TaskRoute:
    __slots__ = ("conn", "lease_id", "worker_id", "node_id", "inflight",
                 "last_used", "batcher", "retired")

    def __init__(self, conn, lease_id: str, worker_id: str,
                 node_id: str = "") -> None:
        self.conn = conn
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.node_id = node_id
        self.inflight = 0
        self.last_used = time.monotonic()
        self.batcher: Optional[_PushBatcher] = None
        # A retired route (controller bounced: its lease ledger is gone)
        # serves its in-flight pushes to completion but takes no new work;
        # the batch done-callback closes the conn once inflight drains.
        self.retired = False


class _TaskRoutePool:
    def __init__(self) -> None:
        self.routes: List[_TaskRoute] = []
        self.lock = threading.Lock()
        self.next_try = 0.0    # monotonic; backoff after failed lease
        self.acquiring = 0     # in-flight _acquire calls (caps pool growth)

    def _acquire_block(self, wc, resources, env_hash, runtime_env,
                       arg_bytes=None, count: int = 1
                       ) -> Optional[_TaskRoute]:
        """ONE lease_block round trip grants up to ``count`` workers; every
        grant becomes a live route, so the wave fans across the block with
        no further controller involvement. Returns the first route born
        checked-out (the caller's task rides it); extra routes join the
        pool idle."""
        from . import protocol

        try:
            got = wc.client.request({
                "kind": "lease_block", "count": max(1, count),
                "resources": resources,
                "env_hash": env_hash, "runtime_env": runtime_env,
                "arg_bytes": arg_bytes or {}})
        except Exception:
            got = None
        grants = (got or {}).get("grants") or []
        if not grants:
            # Empty grant: the cluster has nothing leasable for this
            # signature right now — back off the full window. A PARTIAL
            # grant only keeps the shorter pick()-side growth throttle:
            # punitive backoff there serialized genuinely-parallel work
            # onto one route for the whole window.
            with self.lock:
                self.next_try = time.monotonic() + _LEASE_BACKOFF_S
            return None
        first: Optional[_TaskRoute] = None
        stranded: List[str] = []
        for g in grants:
            try:
                conn = wc.client.io.call(
                    protocol.connect(g["host"], g["port"],
                                     name=f"lease->{g['worker_id'][:8]}"),
                    timeout=5)
            except Exception:
                stranded.append(g["lease_id"])
                continue
            route = _TaskRoute(conn, g["lease_id"], g["worker_id"],
                               g.get("node_id") or "")
            route.batcher = _PushBatcher(
                "direct_task_batch", conn, wc.client.io,
                self._make_batch_done(wc, route))
            if first is None:
                # Born checked-out (inflight=1): a freshly acquired route
                # must never be visible to _reclaim_leases / the idle
                # reaper with inflight==0 while its first submit is still
                # in flight (advisor r4: that window releases the lease
                # under the push and fabricates a WorkerCrashedError on a
                # retry-less task).
                route.inflight = 1
                first = route
            with self.lock:
                self.routes.append(route)
        if stranded:
            try:
                wc.client.conn.request_threadsafe(
                    {"kind": "release_lease", "lease_ids": stranded})
            except Exception:
                pass
        return first

    def _make_batch_done(self, wc, route: "_TaskRoute"):
        """Done-callback for one route's push batches (io thread): settle
        bookkeeping for every spec in the batch, cache the aggregated
        result locations once, and hand transport failures to a recovery
        thread that distinguishes completed entries from never-ran ones."""

        def done(batch: _PushBatch, res, exc) -> None:
            specs = batch.specs
            with self.lock:
                route.inflight -= len(specs)
                route.last_used = time.monotonic()
                close_retired = route.retired and route.inflight <= 0
            if exc is None:
                # Same mark _await_inflight uses: whichever side processes
                # the aggregated payload first spares the other the walk.
                if not getattr(batch.fut, "_rtpu_cached", False):
                    batch.fut._rtpu_cached = True
                    _cache_locs(res.get("locations"))
                    _cache_locs(res.get("error_locations"))
                for spec in specs:
                    for oid in spec.get("return_ids", ()):
                        _inflight_direct.pop(oid, None)
                        _direct_task_meta.pop(oid, None)
                if close_retired:
                    try:
                        wc.client.io.call_nowait(route.conn.close())
                    except Exception:
                        pass
            else:
                for spec in specs:
                    for oid in spec.get("return_ids", ()):
                        _inflight_direct.pop(oid, None)
                        _direct_task_meta.pop(oid, None)
                threading.Thread(
                    target=_direct_batch_task_failure,
                    args=(wc, self, route, list(specs)),
                    daemon=True, name="lease-recover").start()

        return done

    def _release_many(self, wc, routes: List["_TaskRoute"]) -> None:
        """Hand back several leases in ONE framed message + close conns."""
        with self.lock:
            self.routes = [r for r in self.routes if r not in routes]
        ids = [r.lease_id for r in routes]
        if ids:
            try:
                wc.client.conn.request_threadsafe(
                    {"kind": "release_lease", "lease_ids": ids})
            except Exception:
                pass
        for r in routes:
            try:
                wc.client.io.call_nowait(r.conn.close())
            except Exception:
                pass

    def _release(self, wc, route: _TaskRoute) -> None:
        with self.lock:
            if route in self.routes:
                self.routes.remove(route)
        try:
            wc.client.conn.request_threadsafe(
                {"kind": "release_lease", "lease_id": route.lease_id})
        except Exception:
            pass
        try:
            wc.client.io.call_nowait(route.conn.close())
        except Exception:
            pass

    def pick(self, wc, resources, env_hash, runtime_env,
             arg_bytes=None, lease_max: Optional[int] = None
             ) -> Optional[_TaskRoute]:
        """Least-loaded live route; grows the pool synchronously whenever
        every route is busy (one leased worker per concurrent task, the
        reference's lease-per-pending-task shape — async growth would
        serialize two parallel tasks onto one worker) and reaps idle
        leases. `arg_bytes` ({node_id: bytes of this task's args there})
        prefers an unsaturated route on the data node and rides to the
        controller on pool growth so new leases land there too."""
        now = time.monotonic()
        with self.lock:
            # One pass: drop dead routes, reap idle leases, find the
            # least-loaded survivor (this runs per submit — list-building
            # per call showed up in submission profiles). Reap every idle
            # lease: a held lease pins a CPU the scheduler can't use for
            # queued tasks or actor creation. Reaped routes leave the pool
            # BEFORE selection so this submit can't ride a lease being
            # handed back.
            live: List[_TaskRoute] = []
            reap: List[_TaskRoute] = []
            best = None
            for r in self.routes:
                if r.conn.closed.is_set():
                    continue
                if r.inflight == 0 and now - r.last_used > _LEASE_IDLE_S:
                    reap.append(r)
                    continue
                live.append(r)
                if best is None or r.inflight < best.inflight:
                    best = r
            self.routes = live
            for r in reap:
                threading.Thread(target=self._release, args=(wc, r),
                                 daemon=True).start()
            want_local = False
            if arg_bytes and live:
                # Locality preference: an unsaturated route on the node
                # holding the most argument bytes beats the globally
                # least-loaded route (the bytes don't move; the task can).
                data_node = max(arg_bytes, key=arg_bytes.get)
                local = [r for r in live if r.node_id == data_node
                         and r.inflight < _LEASE_PIPELINE]
                if local:
                    best = min(local, key=lambda r: r.inflight)
                else:
                    # No route on the data node: grow toward it (the new
                    # lease request carries arg_bytes, so the controller
                    # grants there) instead of shipping the bytes over the
                    # network forever through an idle wrong-node route.
                    want_local = True
            if lease_max is None:
                lease_max = flags.get("RTPU_TASK_LEASE_MAX")
            # acquiring counts toward the cap: N threads deciding to grow
            # simultaneously must not overshoot lease_max between them.
            need_grow = ((best is None
                          or best.inflight >= _LEASE_PIPELINE
                          or want_local)
                         and len(live) + self.acquiring < lease_max
                         and now >= self.next_try)
            # Bulk negotiation: ask for a whole block up front (the first
            # grow of a wave fills the pool in one RPC; later grows top it
            # up), never past the per-signature lease cap.
            block_n = min(max(1, flags.get("RTPU_LEASE_BLOCK")),
                          lease_max - len(live) - self.acquiring) \
                if need_grow else 0
            if best is not None:
                # Checkout under THIS lock acquisition (advisor r4): the
                # route leaves pick() already counted busy, so the reclaim
                # and idle-reap inflight==0 tests can never select it
                # between pick() returning and the submit landing. The
                # caller decrements on submit failure.
                best.inflight += 1
                best.last_used = now
            if need_grow:
                self.acquiring += block_n
                # Rolling growth throttle: at most one negotiation RPC per
                # window while saturated (a wave would otherwise pay one
                # per submit); an EMPTY grant extends this to the full
                # backoff in _acquire_block.
                self.next_try = now + _LEASE_GROW_THROTTLE_S
        if need_grow:
            try:
                got = self._acquire_block(wc, resources, env_hash,
                                          runtime_env, arg_bytes=arg_bytes,
                                          count=block_n)
            finally:
                with self.lock:
                    self.acquiring -= block_n
            if want_local and got is not None and arg_bytes and \
                    got.node_id != max(arg_bytes, key=arg_bytes.get):
                # Grew FOR locality but the grant landed off the data node
                # (no capacity there): back off further locality growth so
                # a stream of submits doesn't inflate the pool with
                # off-node leases, one lease RPC per task. The off-node
                # route still serves this task.
                with self.lock:
                    self.next_try = time.monotonic() + _LEASE_BACKOFF_S
            if got is not None:
                # The new route is born checked-out; hand back the
                # speculative reservation on the old best.
                if best is not None:
                    with self.lock:
                        best.inflight -= 1
                        best.last_used = time.monotonic()
                best = got
            elif best is not None and not want_local:
                # Growth was ATTEMPTED because every route was saturated,
                # and the grant came back empty: the cluster has no idle
                # worker for this signature right now. Spill THIS submit to
                # the controller queue (which spawns workers / dispatches
                # when one frees) instead of deepening a busy route's
                # serial queue — two long concurrent tasks must not
                # serialize behind one lease while CPUs sit free. Bounded:
                # only the submit that performed the (throttled+backed-off)
                # negotiation spills; the wave keeps riding the pool.
                with self.lock:
                    best.inflight -= 1
                    best.last_used = time.monotonic()
                return None
        return best

    def shutdown(self, wc) -> None:
        self._release_many(wc, list(self.routes))


_task_pools: Dict[Tuple, _TaskRoutePool] = {}
_task_pools_lock = threading.Lock()


def _try_direct_task(wc, spec: Dict[str, Any], opts: Dict[str, Any]) -> bool:
    """Push a plain task to a leased worker; False -> controller path."""
    lease_max = flags.get("RTPU_TASK_LEASE_MAX")
    if (spec.get("pg") is not None
            or spec.get("scheduling", {}).get("type") != "DEFAULT"
            or spec.get("retry_exceptions")  # app-error retry is a
            # controller-queue feature: the direct path reports errors
            # straight back to the caller
            or spec.get("streaming")
            or not lease_max
            or not flags.get("RTPU_DIRECT_DISPATCH")):
        return False
    # Deps guard: a leased worker BLOCKS in get_locations for unresolved
    # deps while its lease pins a CPU — if the producer is still queued at
    # the controller, that pin can starve it forever (the controller path
    # waits for deps BEFORE dispatch, so it can't deadlock this way). Only
    # push when every dep's location is already known locally; ship those
    # as hints so the worker skips the controller lookup entirely.
    deps = spec.get("deps") or ()
    hints = {}
    for d in deps:
        loc = _local_locs.get(d)
        if loc is None:
            return False
        hints[d] = loc
    resources = spec.get("resources") or {"CPU": 1.0}
    env_hash = spec.get("env_hash") or ""
    key = (wc.client.token, env_hash,
           tuple(sorted(resources.items())))
    with _task_pools_lock:
        pool = _task_pools.get(key)
        if pool is None:
            pool = _task_pools[key] = _TaskRoutePool()
    # pick() returns the route already checked out (inflight counted under
    # the pool lock) — decrement on any failure to submit.
    arg_bytes: Dict[str, int] = {}
    for loc in hints.values():
        if loc.node_id and loc.inline is None:
            arg_bytes[loc.node_id] = arg_bytes.get(loc.node_id, 0) + loc.size
    route = pool.pick(wc, resources, env_hash, spec.get("runtime_env"),
                      arg_bytes=arg_bytes, lease_max=lease_max)
    if route is None:
        return False
    if hints:
        # Only the secured direct route carries cached-location hints: the
        # controller fallback re-resolves locations itself, and a hint that
        # went stale while queued there would turn a recoverable miss into
        # a task read failure (advisor r4).
        spec["loc_hints"] = hints
    if flags.get("RTPU_SUBMIT_BATCH"):
        # Batched push: the spec rides the route's open multi-spec frame;
        # bookkeeping (inflight maps, location caching, failure recovery)
        # is settled per batch by the route's done callback.
        route.batcher.add(spec, spec.get("return_ids", ()),
                          meta=(spec["task_id"], route.conn))
        return True
    try:
        fut = route.conn.request_threadsafe(
            {"kind": "direct_task", "spec": spec})
    except Exception:
        spec.pop("loc_hints", None)  # controller fallback re-resolves
        with pool.lock:
            route.inflight -= 1
        return False
    for oid in spec.get("return_ids", ()):
        _inflight_direct[oid] = fut
        _direct_task_meta[oid] = (spec["task_id"], route.conn)

    def done(f, wc=wc, pool=pool, route=route, spec=spec):
        with pool.lock:
            route.inflight -= 1
            route.last_used = time.monotonic()
            close_retired = route.retired and route.inflight <= 0
        if close_retired:
            try:
                wc.client.io.call_nowait(route.conn.close())
            except Exception:
                pass
        for oid in spec.get("return_ids", ()):
            _inflight_direct.pop(oid, None)
            _direct_task_meta.pop(oid, None)
        exc = f.exception()
        if exc is None:
            res = f.result() or {}
            for loc in (res.get("locations") or ()):
                _cache_loc(loc)
            for loc in (res.get("error_locations") or ()):
                _cache_loc(loc)
        else:
            # Worker/connection died mid-push. The direct attempt counts
            # against max_retries exactly like a controller-tracked attempt
            # (the task may have partially executed — re-running a
            # max_retries=0 task would violate its at-most-once contract).
            # Off the io thread: recovery issues blocking RPCs.
            threading.Thread(
                target=_direct_task_failure, args=(wc, pool, route, spec),
                daemon=True, name="lease-recover").start()

    fut.add_done_callback(done)
    return True


def _direct_task_failure(wc, pool: "_TaskRoutePool", route: "_TaskRoute",
                         spec: Dict[str, Any]) -> None:
    pool._release(wc, route)
    _requeue_or_fail_direct_task(wc, route, spec)


def _requeue_or_fail_direct_task(wc, route: "_TaskRoute",
                                 spec: Dict[str, Any]) -> None:
    """The push failed and the task did NOT provably complete. The direct
    attempt counts against max_retries exactly like a controller-tracked
    attempt; with no budget left the at-most-once contract stands and the
    task fails with WorkerCrashedError."""
    retries = int(spec.get("max_retries", 0))
    if retries > 0:
        spec = dict(spec, max_retries=retries - 1)
        # The hints plausibly point at objects hosted on the worker that
        # just crashed — the controller path must re-resolve fresh.
        spec.pop("loc_hints", None)
        try:
            _track_inflight(spec)  # it now rides the controller queue
            _pipelined_submit(wc, {"kind": "submit_task", "spec": spec},
                              spec.get("return_ids", ()))
        except Exception:
            pass
        return
    import pickle as _p

    from .controller import WorkerCrashedError
    from .object_store import ObjectLocation

    err = WorkerCrashedError(
        f"worker {route.worker_id[:8]} died while running directly-pushed "
        f"task {spec.get('label', '')} (no retries left)")
    data = _p.dumps(err)
    for oid in spec.get("return_ids", ()):
        loc = ObjectLocation(object_id=oid, size=len(data), inline=data,
                             is_error=True)
        if oid not in _local_locs:
            _cache_loc(loc)
        try:
            wc.client.request(
                {"kind": "put_location", "loc": loc, "if_absent": True})
        except Exception:
            pass


def _direct_batch_task_failure(wc, pool: "_TaskRoutePool",
                               route: "_TaskRoute",
                               specs: List[Dict[str, Any]]) -> None:
    """A batched push failed mid-flight (worker death / dead connection).
    Entries that already completed published their result locations to the
    controller through the worker's completion batcher — ONE wait probe
    sorts the batch into completed (cache, never re-run: no duplication)
    and unacked (re-route through the controller: no loss)."""
    pool._release(wc, route)
    all_ids = [oid for s in specs for oid in (s.get("return_ids") or ())]
    done_ids: set = set()
    if all_ids:
        try:
            ready = wc.client.request(
                {"kind": "wait", "object_ids": all_ids,
                 "num_returns": len(all_ids), "timeout": 0})
            done_ids = set(ready or ())
        except Exception:
            done_ids = set()
        if done_ids:
            try:
                locs = wc.client.request(
                    {"kind": "get_locations",
                     "object_ids": sorted(done_ids), "timeout": 1})
                for loc in locs.values():
                    _cache_loc(loc)
            except Exception:
                pass  # get() re-asks the controller; completion stands
    for spec in specs:
        rids = spec.get("return_ids") or ()
        if rids and all(oid in done_ids for oid in rids):
            continue  # completed and published before the route died
        _requeue_or_fail_direct_task(wc, route, spec)


def _pipelined_submit(wc, msg: Dict[str, Any], return_ids) -> None:
    """Submit without waiting for the controller's ack (the reply is
    pipelined on the connection, so ordering with later requests holds).
    A connection drop retries through the client's reconnect path (the
    controller may just be bouncing — puts/submits in flight survive);
    a real submission failure surfaces as error locations on the return
    ids — the same channel task-execution errors use."""
    fut = wc.client.conn.request_threadsafe(msg)

    def fail(exc, return_ids):
        import pickle as _p
        import sys as _sys

        from .object_store import ObjectLocation

        # Fire-and-forget callers never get() these refs — make sure the
        # failure is at least visible somewhere.
        _sys.stderr.write(f"[ray_tpu] pipelined submit failed: {exc!r}\n")
        data = _p.dumps(exc if isinstance(exc, Exception)
                        else RuntimeError(repr(exc)))
        for oid in return_ids:
            loc = ObjectLocation(object_id=oid, size=len(data), inline=data,
                                 is_error=True)
            _cache_loc(loc)
            try:
                wc.client.send_nowait({"kind": "put_location", "loc": loc})
            except Exception:
                pass

    def done(f, wc=wc, msg=msg, return_ids=tuple(return_ids)):
        exc = f.exception()
        if exc is None:
            return
        if (isinstance(exc, ConnectionError)
                and wc.client.reconnect_enabled
                and not wc.client._closed):
            # Controller bounce mid-flight: re-issue through the blocking
            # client (it reconnects with backoff) off the io thread.
            def _retry():
                try:
                    wc.client.request(msg)
                except Exception as e2:  # noqa: BLE001
                    fail(e2, return_ids)

            threading.Thread(target=_retry, daemon=True,
                             name="submit-retry").start()
            return
        fail(exc, return_ids)

    fut.add_done_callback(done)


def _await_inflight(ids, timeout: Optional[float]) -> None:
    """Wait for in-flight direct replies covering `ids` (their locations
    land in _local_locs via the completion callback). Batched pushes share
    one future across many return ids — each distinct future is awaited
    and its aggregated payload processed once, not once per id."""
    deadline = None if timeout is None else time.monotonic() + timeout
    seen: set = set()
    for oid in ids:
        fut = _inflight_direct.get(oid)
        if fut is None or id(fut) in seen:
            continue
        seen.add(id(fut))
        try:
            res = fut.result(None if deadline is None
                             else max(0.0, deadline - time.monotonic()))
        except Exception:
            # Failure recovery (error locations) happens in the done
            # callback / recovery thread; fall through to the controller.
            continue
        # Cache here too: the done-callback runs on the io thread and may
        # not have fired yet when result() unblocks (idempotent with it;
        # the _rtpu_cached mark keeps a 500-entry batch from being
        # re-walked for every one of its ids).
        if getattr(fut, "_rtpu_cached", False):
            continue
        fut._rtpu_cached = True
        _cache_locs((res or {}).get("locations"))
        _cache_locs((res or {}).get("error_locations"))


def exit_actor() -> None:
    """Reference: ray.actor.exit_actor — terminate the hosting actor after
    the current call (implemented in core.worker; re-exported here for the
    package root)."""
    from .worker import exit_actor as _exit_actor

    _exit_actor()


def method(*, num_returns: int = 1):
    """Per-method defaults (reference: @ray.method(num_returns=N)) —
    consumed when the actor class registers, carried on every handle."""
    def deco(fn):
        fn.__rtpu_method_opts__ = {"num_returns": num_returns}
        return fn

    return deco


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns=1,
                 deadline_s=None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._deadline_s = deadline_s

    def options(self, num_returns=1, deadline_s=None) -> "ActorMethod":
        return ActorMethod(self._handle, self._name, num_returns, deadline_s)

    def remote(self, *args, **kwargs):
        return self._handle._submit(self._name, args, kwargs,
                                    self._num_returns,
                                    deadline_s=self._deadline_s)

    def bind(self, *args, **kwargs):
        """Lazy DAG node for this method on an existing actor handle."""
        from ray_tpu.dag.dag_node import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)


class ActorHandle:
    """Client-side handle to an actor (reference: actor.py ActorHandle)."""

    def __init__(self, actor_id: str, method_names: Sequence[str],
                 method_defaults: Optional[Dict[str, Dict[str, Any]]] = None,
                 replayable: bool = False):
        self._actor_id = actor_id
        self._method_names = list(method_names)
        self._method_defaults = dict(method_defaults or {})
        # max_task_retries actor: calls carry the replay flag, so a failed
        # path may resubmit them without a never-ran proof (the actor's
        # exactly-once journal dedups any that actually executed).
        self._replayable = bool(replayable)
        # Per-method static spec template (see RemoteFunction._tmpl): a
        # call serializes only its args, ids and seqno; batched pushes
        # pickle the shared fields once per frame.
        self._tmpls: Dict[str, Dict[str, Any]] = {}

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        if self._method_names and name not in self._method_names:
            raise AttributeError(f"actor has no method {name!r}")
        return ActorMethod(self, name,
                           self._method_defaults.get(name, {}).get(
                               "num_returns", 1))

    def _submit(self, method: str, args, kwargs, num_returns,
                deadline_s=None):
        wc = ctx.get_worker_context()
        streaming = num_returns == "streaming"
        args_blob, deps, nested_refs = pack_args(args, kwargs)
        n_rets = 0 if streaming else max(num_returns, 0)
        return_ids = [ObjectID.generate() for _ in range(n_rets)]
        tmpl = self._tmpls.get(method)
        if tmpl is None:
            tmpl = self._tmpls[method] = {
                "actor_id": self._actor_id,
                "method_name": method,
                "resources": {},
                "label": f"actor.{method}",
                **({"replay": True} if self._replayable else {}),
                # "caller" anchors the per-(caller, actor) sequence
                # numbers: calls from one caller can ride different paths
                # (direct socket vs controller fallback) and overtake each
                # other; the mailbox restores submission order (reference:
                # direct_actor_task_submitter's per-caller sequence_no).
                "caller": ownership.process_token(),
            }
        spec = dict(tmpl)
        spec["task_id"] = TaskID.generate()
        spec["args_blob"] = args_blob
        spec["deps"] = deps
        spec["return_ids"] = return_ids
        spec["seqno"] = _next_actor_seqno(self._actor_id)
        if deadline_s is not None:
            # Absolute deadline: mailbox dequeue drops the call once it
            # passes instead of executing dead work.
            spec["deadline_ts"] = time.time() + float(deadline_s)
        ptid = ctx.current_task_id()
        if ptid:
            spec["parent_task_id"] = ptid
        if streaming:
            _streaming_spec_opts({}, spec)
        if deps or nested_refs:
            _register_dep_holds(spec, nested_refs)
        tracing.inject_submit_span(spec, spec["label"])
        if flags.get("RTPU_TASK_EVENTS"):
            spec["submit_ts"] = time.time()
        submitted = False
        if not streaming and flags.get("RTPU_DIRECT_DISPATCH"):
            route = _get_route(wc, self._actor_id)
            if route.conn is not None or _resolve_route(
                    wc, route, self._actor_id):
                hints = {d: _local_locs[d] for d in deps if d in _local_locs}
                if hints:
                    spec["loc_hints"] = hints
                submitted = _direct_submit(wc, route, spec)
        if not submitted:
            wc.client.request({"kind": "submit_actor_task", "spec": spec})
        elif "parent_task_id" in spec:
            _note_task_lineage(wc, spec)
        if streaming:
            return ObjectRefGenerator(spec["task_id"])
        refs = _claim_return_refs(return_ids)
        if num_returns == 1:
            return refs[0]
        if num_returns == 0:
            return None
        return refs

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_names,
                              self._method_defaults, self._replayable))

    def __repr__(self) -> str:
        return f"ActorHandle({self._actor_id[:16]})"


class ActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = options or {}
        self._func_id: Optional[str] = None
        self._registered_with: Optional[str] = None

    def options(self, **opts) -> "ActorClass":
        new = ActorClass(self._cls, {**self._options, **opts})
        new._func_id = self._func_id
        new._registered_with = self._registered_with
        return new

    def _ensure_registered(self, wc: ctx.WorkerContext) -> str:
        key = wc.client.token
        if self._func_id is None or self._registered_with != key:
            blob = cloudpickle.dumps(self._cls)
            func_id = TaskID.generate()
            wc.client.request({"kind": "register_function", "func_id": func_id, "blob": blob})
            self._func_id = func_id
            self._registered_with = key
        return self._func_id

    def remote(self, *args, **kwargs) -> ActorHandle:
        wc = ctx.get_worker_context()
        func_id = self._ensure_registered(wc)
        opts = self._options
        resources = dict(opts.get("resources", {}) or {})
        # Actors default to 0 CPU while alive (reference semantics — this is
        # what lets 40k actors coexist on a node; ray actor.py default).
        resources["CPU"] = float(opts.get("num_cpus", 0))
        if opts.get("num_tpus"):
            resources["TPU"] = float(opts["num_tpus"])
        _validate_accel_resources(resources)
        strategy, pg = _normalize_strategy(opts.get("scheduling_strategy"))
        args_blob, deps, nested_refs = pack_args(args, kwargs)
        actor_id = ActorID.generate()
        method_names = [
            n for n in dir(self._cls)
            if not n.startswith("_") and callable(getattr(self._cls, n, None))
        ]
        # Crash-consistent fault tolerance (reference: ray actor options
        # max_restarts/max_task_retries + the Ray paper's actor
        # checkpointing): checkpoint_interval_s / checkpoint_every_n make
        # the hosting worker durably checkpoint the instance (plus the
        # exactly-once call journal); max_task_retries != 0 (-1 = always)
        # opts method calls into replay-on-failure — retried calls dedup
        # against the journal, so replay is exactly-once, not at-least.
        max_task_retries = int(opts.get("max_task_retries", 0))
        spec = {
            "task_id": TaskID.generate(),
            "actor_id": actor_id,
            "func_id": func_id,
            "args_blob": args_blob,
            "deps": deps,
            "return_ids": [],
            "resources": {k: v for k, v in resources.items() if v},
            "scheduling": strategy,
            "pg": pg,
            "name": opts.get("name"),
            "namespace": wc.namespace,
            "detached": opts.get("lifetime") == "detached",
            "max_concurrency": opts.get("max_concurrency", 1),
            "max_restarts": int(opts.get("max_restarts", 0)),
            "max_task_retries": max_task_retries,
            "checkpoint_interval_s": float(
                opts.get("checkpoint_interval_s") or 0.0),
            "checkpoint_every_n": int(opts.get("checkpoint_every_n") or 0),
            "label": f"{self._cls.__name__}.__init__",
        }
        _attach_runtime_env(wc, opts, spec)
        _register_dep_holds(spec, nested_refs)
        tracing.inject_submit_span(spec, spec["label"])
        wc.client.request({"kind": "create_actor", "spec": spec})
        method_defaults = {
            n: getattr(getattr(self._cls, n), "__rtpu_method_opts__")
            for n in method_names
            if hasattr(getattr(self._cls, n, None), "__rtpu_method_opts__")
        }
        wc.client.request(
            {"kind": "kv_put", "ns": "__actor_methods__", "key": actor_id,
             "value": cloudpickle.dumps(
                 (method_names, method_defaults,
                  {"replayable": bool(max_task_retries)}))}
        )
        return ActorHandle(actor_id, method_names, method_defaults,
                           replayable=bool(max_task_retries))

    def bind(self, *args, **kwargs):
        """Lazy actor construction node (reference python/ray/dag/class_node.py)."""
        from ray_tpu.dag.dag_node import ClassNode

        return ClassNode(self, args, kwargs)


def remote(*args, **kwargs):
    """``@remote`` decorator for functions and classes, with option form
    ``@remote(num_cpus=..., num_tpus=..., resources=..., ...)``."""

    def wrap(target):
        if isinstance(target, type):
            return ActorClass(target, kwargs)
        return RemoteFunction(target, kwargs)

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return wrap(args[0])
    if args:
        raise TypeError("use @remote or @remote(**options)")
    return wrap


def cancel(ref: ObjectRef, *, force: bool = False,
           recursive: bool = False) -> None:
    """Cancel the task producing ``ref`` (reference: ray.cancel). Queued
    tasks fail immediately with TaskCancelledError — no worker round-trip;
    running tasks get the exception raised in their executing thread
    (force=True kills the hosting worker instead, for code that swallows
    exceptions). recursive=True also cancels every live descendant task
    via the controller's ownership tree. Cancelling a finished ref (or
    cancelling twice) is a no-op."""
    wc = ctx.get_worker_context()
    meta = _direct_task_meta.get(ref.object_id)
    if meta is not None and not force and not recursive:
        # Directly-pushed task: the controller never saw the spec — the
        # cancel rides the same lease connection the push did.
        task_id, conn = meta
        try:
            wc.client.io.call_nowait(conn.send(
                {"kind": "cancel_task", "task_id": task_id}))
            return
        except Exception:
            pass  # route died: the crash path fails the task anyway
    msg = {"kind": "cancel_task", "object_id": ref.object_id,
           "force": force, "recursive": recursive}
    tid = _inflight_oid2task.get(ref.object_id)
    if tid is not None:
        # Controller-routed task: name it outright so a recursive cancel
        # of an already-FINISHED parent can still walk the ownership tree
        # (the return-oid scan only finds live specs).
        msg["task_id"] = tid
    if meta is not None:
        # Direct push + recursive: the controller holds only the lineage
        # note, keyed by task id — send it so the walk can start there,
        # and reach the task itself through the lease route as usual.
        msg["task_id"] = meta[0]
        task_id, conn = meta
        try:
            wc.client.io.call_nowait(conn.send(
                {"kind": "cancel_task", "task_id": task_id}))
        except Exception:
            pass
    wc.client.request(msg)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    wc = ctx.get_worker_context()
    wc.client.request({"kind": "kill_actor", "actor_id": actor._actor_id})


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    wc = ctx.get_worker_context()
    info = wc.client.request(
        {"kind": "get_named_actor", "name": name, "namespace": namespace or wc.namespace}
    )
    methods_blob = wc.client.request(
        {"kind": "kv_get", "ns": "__actor_methods__", "key": info["actor_id"]}
    )
    blob = cloudpickle.loads(methods_blob) if methods_blob else []
    meta: Dict[str, Any] = {}
    if isinstance(blob, tuple):
        if len(blob) >= 3:
            methods, defaults, meta = blob[0], blob[1], blob[2] or {}
        else:
            methods, defaults = blob
    else:  # pre-@method registrations stored a bare name list
        methods, defaults = blob, {}
    return ActorHandle(info["actor_id"], methods, defaults,
                       replayable=bool(meta.get("replayable")))


# --------------------------------------------------------------- cluster info


def cluster_resources() -> Dict[str, float]:
    wc = ctx.get_worker_context()
    state = wc.client.request({"kind": "cluster_state"})
    out: Dict[str, float] = {}
    for n in state["nodes"]:
        for k, v in n["resources"].items():
            out[k] = out.get(k, 0.0) + v
    return out


def available_resources() -> Dict[str, float]:
    wc = ctx.get_worker_context()
    state = wc.client.request({"kind": "cluster_state"})
    out: Dict[str, float] = {}
    for n in state["nodes"]:
        if not n.get("alive", True):
            # A dead node's snapshot freezes at its last report; counting
            # it advertises capacity the scheduler can no longer place on.
            continue
        for k, v in n["available"].items():
            out[k] = out.get(k, 0.0) + v
    return out


def nodes() -> List[Dict[str, Any]]:
    wc = ctx.get_worker_context()
    return ctx.get_worker_context().client.request({"kind": "cluster_state"})["nodes"]


@dataclass
class RuntimeContext:
    node_id: str
    namespace: str
    task_id: Optional[str]
    actor_id: Optional[str]

    def get_node_id(self) -> str:
        return self.node_id

    def get_accelerator_ids(self) -> Dict[str, List[str]]:
        """Accelerator ids assigned to this worker process, per resource
        name (reference: worker.py:932 get_accelerator_ids_for_accelerator_
        resource over CUDA_VISIBLE_DEVICES/TPU_VISIBLE_CHIPS). Workers
        spawned for a TPU request see the chip ids the spawner granted;
        an empty list means no assignment (unrestricted visibility)."""
        from ray_tpu.util.accelerators import accelerator_managers

        out: Dict[str, List[str]] = {}
        for mgr in accelerator_managers():
            out[mgr.resource_name] = mgr.get_visible_ids() or []
        return out


def get_runtime_context() -> RuntimeContext:
    wc = ctx.get_worker_context()
    return RuntimeContext(
        node_id=wc.node_id,
        namespace=wc.namespace,
        task_id=ctx.current_task_id(),
        actor_id=ctx.current_actor_id(),
    )
