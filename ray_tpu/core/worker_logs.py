"""Per-worker log files (reference: session_latest/logs/worker-*.out).

Spawners (controller, host agent) redirect worker stdout/stderr here; the
worker's own tee (worker.py) forwards lines to drivers, so inheriting the
console would print everything twice on single-host setups. The file is
the durable copy, the driver console gets the prefixed stream.
"""
from __future__ import annotations

import os
import tempfile
from typing import IO, Optional

from ray_tpu import flags


def log_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "rtpu_logs")


def worker_log_file(spawn_token: str) -> Optional[IO[bytes]]:
    """Open the spawn's log file for redirect; None -> inherit the console.

    Restart-churned tokens reuse files; a file past RTPU_WORKER_LOG_MAX is
    truncated on (re)open — the crude rotation that keeps a long-lived
    autoscaling host from filling /tmp.
    """
    try:
        d = log_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"worker-{spawn_token[:12]}.out")
        cap = flags.get("RTPU_WORKER_LOG_MAX")
        mode = "ab"
        try:
            if os.path.getsize(path) > cap:
                mode = "wb"
        except OSError:
            pass
        return open(path, mode)
    except OSError:
        return None
