"""Per-worker log files: attribution, rotation, and fetch helpers.

Reference surfaces collapsed into one module (ray:
session_latest/logs/worker-*.out + the log_monitor magic-line protocol +
the dashboard/CLI log endpoints reading any file on any node):

- Spawners (controller, host agent) redirect worker stdout/stderr here via
  :func:`worker_log_file`; the worker's own tee (worker.py) forwards lines
  to drivers, so inheriting the console would print everything twice on
  single-host setups. The file is the durable copy.
- A file past ``RTPU_WORKER_LOG_MAX`` rotates to a single ``.1`` backup on
  (re)open — history survives rotation instead of being truncated away.
- :class:`LogAttributor` stamps structured attribution markers (task id,
  actor id, worker, node, label) into the stream whenever the execution
  context changes, and maintains a JSONL sidecar index
  (``worker-*.out.idx``) of task/actor -> byte-range so one task's output
  is retrievable without scanning the file (the reference's magic-line
  attribution, made O(ranges) on the read path).
- :func:`serve_get_log` / :func:`serve_get_log_wait` implement the
  ``get_log`` RPC body shared by the host agent and the controller's
  local-node path: ranged reads, task/actor-filtered reads over the index,
  and long-poll follow mode.

Everything attribution-side is gated on ``RTPU_LOG_ATTRIBUTION``: when
off, a worker's write path pays one flag check per write and no marker or
index I/O happens.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import IO, Any, Dict, List, Optional, Tuple

from ray_tpu import flags

# A marker line opens each attribution segment in the log file itself, so
# the file remains self-describing even if the sidecar index is lost.
MARKER_PREFIX = "::rtpu-log::"

# Pending in-memory index ranges flush at this size so a crashing worker
# loses at most one bounded range (idx appends are line-buffered).
_PENDING_FLUSH_BYTES = 64 * 1024


def log_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "rtpu_logs")


def log_file_name(spawn_token: str) -> str:
    return f"worker-{spawn_token[:12]}.out"


def rotate_log(path: str) -> None:
    """path -> path.1 (replacing any previous backup); the index sidecar
    moves with it so byte ranges always refer to the file they index."""
    os.replace(path, path + ".1")
    try:
        if os.path.exists(path + ".idx"):
            os.replace(path + ".idx", path + ".1.idx")
    except OSError:
        pass


def worker_log_file(spawn_token: str) -> Optional[IO[bytes]]:
    """Open the spawn's log file for redirect; None -> inherit the console.

    Restart-churned tokens reuse files; a file past RTPU_WORKER_LOG_MAX is
    rotated to a ``.1`` backup on (re)open, keeping a long-lived
    autoscaling host from filling /tmp without dropping the prior history
    on the floor.
    """
    try:
        d = log_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, log_file_name(spawn_token))
        cap = flags.get("RTPU_WORKER_LOG_MAX")
        try:
            if os.path.getsize(path) > cap:
                rotate_log(path)
        except OSError:
            pass
        return open(path, "ab")
    except OSError:
        return None


# ---------------------------------------------------------------- writer side


class LogAttributor:
    """Task/actor attribution for one worker process's log file.

    One instance is shared by the stdout and stderr tees (both fds are
    dup'ed onto the same O_APPEND file description, so a flush-then-tell on
    either stream reads the true shared end-of-file offset). Under one
    lock it stamps a marker line whenever the execution context changes,
    writes the payload, and records (context, byte-range) entries into the
    line-buffered JSONL sidecar index.
    """

    def __init__(self, spawn_token: str, worker_id: str, node_id: str):
        self.path = os.path.join(log_dir(), log_file_name(spawn_token))
        self.worker_id = worker_id
        self.node_id = node_id
        self.lock = threading.Lock()
        self._last_key: Optional[Tuple] = None
        # [task_id, actor_id, stream, start, end] awaiting an index write.
        self._pending: Optional[list] = None
        self._at_bol = True  # markers must start at a line boundary
        self._idx = open(self.path + ".idx", "a", buffering=1)

    @classmethod
    def create(cls, worker_id: str, node_id: str) -> Optional["LogAttributor"]:
        """None unless this process's stdout actually IS the spawn's log
        file (the spawner's redirect): markers and byte offsets are only
        meaningful there — a worker inheriting a real console must never
        be stamped."""
        import sys

        token = flags.get("RTPU_SPAWN_TOKEN")
        if not token:
            return None
        path = os.path.join(log_dir(), log_file_name(token))
        try:
            if os.fstat(sys.stdout.fileno()).st_ino != os.stat(path).st_ino:
                return None
            return cls(token, worker_id, node_id)
        except (OSError, ValueError, AttributeError):
            return None

    def write(self, inner, text: str, stream: str, task_id: Optional[str],
              actor_id: Optional[str], label: Optional[str]) -> int:
        key = (task_id, actor_id)
        with self.lock:
            try:
                if key != self._last_key:
                    self._stamp(inner, key, stream, label)
                attributed = task_id is not None or actor_id is not None
                start = self._tell(inner) if attributed else None
                n = inner.write(text)
                if text:
                    self._at_bol = text.endswith("\n")
                if start is not None:
                    end = self._tell(inner)
                    if end is not None and end > start:
                        self._record(task_id, actor_id, stream, start, end)
                return n
            except Exception:
                # Attribution must never take the write path down; fall
                # back to the plain write if bookkeeping failed mid-way.
                try:
                    return inner.write(text)
                except Exception:
                    return 0

    def _stamp(self, inner, key: Tuple, stream: str,
               label: Optional[str]) -> None:
        self._flush_pending()
        marker = MARKER_PREFIX + json.dumps(
            {"task_id": key[0], "actor_id": key[1],
             "worker_id": self.worker_id, "node_id": self.node_id,
             "label": label, "stream": stream,
             "ts": round(time.time(), 3)},
            separators=(",", ":")) + "\n"
        if not self._at_bol:
            marker = "\n" + marker
        inner.write(marker)
        self._at_bol = True
        self._last_key = key

    @staticmethod
    def _tell(inner) -> Optional[int]:
        """True byte offset of the shared log fd: flush Python's buffer,
        then ask the binary layer (self-correcting against any out-of-band
        fd writes by C extensions)."""
        try:
            inner.flush()
            return inner.buffer.tell()
        except (OSError, ValueError, AttributeError):
            return None

    def _record(self, task_id, actor_id, stream, start: int,
                end: int) -> None:
        p = self._pending
        if (p is not None and (p[0], p[1], p[2]) == (task_id, actor_id,
                                                     stream)
                and p[4] == start):
            p[4] = end  # contiguous same-context write: extend in place
        else:
            self._flush_pending()
            self._pending = [task_id, actor_id, stream, start, end]
        if self._pending[4] - self._pending[3] >= _PENDING_FLUSH_BYTES:
            self._flush_pending()

    def _flush_pending(self) -> None:
        p, self._pending = self._pending, None
        if p is None:
            return
        try:
            self._idx.write(json.dumps(
                {"t": p[0], "a": p[1], "st": p[2], "s": p[3], "e": p[4]},
                separators=(",", ":")) + "\n")
        except Exception:
            pass

    def flush(self) -> None:
        """Flush the pending index range (task-completion hook: a task's
        last lines must be indexed by the time its result is observable
        modulo one scheduling beat)."""
        with self.lock:
            self._flush_pending()


# ---------------------------------------------------------------- reader side


def strip_marker_lines(text: str) -> str:
    if MARKER_PREFIX not in text:
        return text
    return "\n".join(line for line in text.split("\n")
                     if not line.startswith(MARKER_PREFIX))


def read_tail(path: str, nbytes: int = 65536) -> str:
    """Last ``nbytes`` of a log file, attribution markers stripped."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        f.seek(max(0, size - nbytes))
        text = f.read(nbytes).decode("utf-8", "replace")
    return strip_marker_lines(text)


def list_log_files() -> List[Dict[str, Any]]:
    """[{name, size, mtime}] for every worker log (backups included,
    sidecar indexes excluded) in this host's log dir."""
    out: List[Dict[str, Any]] = []
    d = log_dir()
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        if not name.startswith("worker-") or name.endswith(".idx"):
            continue
        try:
            st = os.stat(os.path.join(d, name))
        except OSError:
            continue
        out.append({"name": name, "size": st.st_size, "mtime": st.st_mtime})
    return out


def log_volume_bytes() -> int:
    """Total bytes under the log dir (files + sidecars): the per-node
    log-volume gauge shipped in agent heartbeats."""
    total = 0
    try:
        with os.scandir(log_dir()) as it:
            for e in it:
                try:
                    if e.is_file():
                        total += e.stat().st_size
                except OSError:
                    pass
    except OSError:
        return 0
    return total


def task_ranges(path: str, task_id: Optional[str] = None,
                actor_id: Optional[str] = None) -> List[List[int]]:
    """Merged [start, end) byte ranges of one task's (or actor's) output,
    from the sidecar index — no log-file scan."""
    ranges: List[List[int]] = []
    try:
        with open(path + ".idx", "r", encoding="utf-8") as f:
            for line in f:
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                if task_id is not None and r.get("t") != task_id:
                    continue
                if actor_id is not None and r.get("a") != actor_id:
                    continue
                s, e = int(r["s"]), int(r["e"])
                if ranges and s <= ranges[-1][1]:
                    ranges[-1][1] = max(ranges[-1][1], e)
                else:
                    ranges.append([s, e])
    except OSError:
        pass
    return ranges


def read_task_output(path: str, task_id: Optional[str] = None,
                     actor_id: Optional[str] = None, offset: int = 0,
                     max_bytes: int = 65536) -> Tuple[str, int, int]:
    """(data, new_offset, total_bytes) of one task's attributed output.

    ``offset`` indexes into the task's concatenated output (not the file),
    so followers can stream a single task's lines incrementally; negative
    offsets count back from the current end.
    """
    ranges = task_ranges(path, task_id, actor_id)
    total = sum(e - s for s, e in ranges)
    if offset < 0:
        offset = max(0, total + offset)
    out: List[bytes] = []
    skip, budget = offset, max_bytes
    try:
        with open(path, "rb") as f:
            for s, e in ranges:
                if budget <= 0:
                    break
                n = e - s
                if skip >= n:
                    skip -= n
                    continue
                s += skip
                skip = 0
                take = min(e - s, budget)
                f.seek(s)
                out.append(f.read(take))
                budget -= take
    except OSError:
        return "", offset, total
    raw = b"".join(out)
    return raw.decode("utf-8", "replace"), offset + len(raw), total


def serve_get_log(msg: Dict[str, Any]) -> Dict[str, Any]:
    """``get_log`` RPC body (host agent + controller local path): a ranged
    read of one log file, or an index-backed read of one task's/actor's
    output when ``task_id``/``actor_id`` is set. Returns {data, offset,
    size, eof} — ``offset`` is the resume cursor for follow mode."""
    name = os.path.basename(msg.get("name") or "")
    path = os.path.join(log_dir(), name)
    offset = int(msg.get("offset") or 0)
    max_bytes = min(int(msg.get("max_bytes") or 65536), 1 << 20)
    task_id, actor_id = msg.get("task_id"), msg.get("actor_id")
    try:
        if task_id or actor_id:
            data, new_off, total = read_task_output(
                path, task_id, actor_id, offset, max_bytes)
            return {"data": data, "offset": new_off, "size": total,
                    "eof": new_off >= total}
        size = os.path.getsize(path)
        if offset < 0:
            offset = max(0, size + offset)
        offset = min(offset, size)
        with open(path, "rb") as f:
            f.seek(offset)
            raw = f.read(max_bytes)
        text = raw.decode("utf-8", "replace")
        if msg.get("strip_markers", True):
            text = strip_marker_lines(text)
        return {"data": text, "offset": offset + len(raw), "size": size,
                "eof": offset + len(raw) >= size}
    except OSError as e:
        return {"error": str(e), "data": "", "offset": offset, "size": 0,
                "eof": True}


async def serve_get_log_wait(msg: Dict[str, Any]) -> Dict[str, Any]:
    """Long-poll wrapper: with ``wait_s`` set, hold the reply until new
    bytes appear past ``offset`` (or the window closes). Follow mode is a
    chain of these — each one an independent request on the caller's
    reconnecting client, so streams pause across a controller bounce and
    resume on re-register instead of dying."""
    import asyncio

    deadline = time.monotonic() + min(float(msg.get("wait_s") or 0), 10.0)
    while True:
        out = serve_get_log(msg)
        if out.get("data") or out.get("error") \
                or time.monotonic() >= deadline:
            return out
        await asyncio.sleep(0.15)
