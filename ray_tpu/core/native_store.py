"""ctypes binding to the native shared-memory object arena.

Role-equivalent to the reference's plasma client (ray:
python/ray/_private: plasma usage via CoreWorkerPlasmaStoreProvider,
src/ray/object_manager/plasma/client.cc), minus the store daemon: every
process maps the same arena and calls into librtpu_store.so directly; the
robust in-arena mutex replaces the client/server socket protocol.

The library is built on demand from src/store (g++, no deps) and cached in
ray_tpu/_native/. Everything degrades gracefully: if the toolchain or the
arena is unavailable, callers fall back to the per-object SharedMemory path
in object_store.py.
"""
from __future__ import annotations

from ray_tpu import flags

import ctypes
import logging
import os
import subprocess
import threading
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC_DIR = os.path.join(os.path.dirname(_PKG_ROOT), "src", "store")


def _lib_path() -> str:
    # RTPU_STORE_LIB selects an alternate build — the asan/tsan variants
    # (src/store/Makefile) load through here so the sanitizer suite runs
    # the exact same Python call paths against instrumented native code.
    return flags.get("RTPU_STORE_LIB") or os.path.join(
        _PKG_ROOT, "_native", "librtpu_store.so")

_lib = None
_lib_failed = False  # a failed build/load is cached: retrying every call
_lib_lock = threading.Lock()  # would re-run make on each large put


def _build() -> bool:
    if not os.path.isdir(_SRC_DIR):
        return False
    try:
        # Serialize concurrent builds (a fleet of workers spawning after a
        # source edit would otherwise all run make at once); the Makefile's
        # atomic link-then-rename keeps readers safe, the lock keeps the
        # compilers from duplicating work. The lock lives in the (gitignored)
        # output dir, not the source tree.
        import fcntl

        out_dir = os.path.dirname(_lib_path())
        os.makedirs(out_dir, exist_ok=True)
        lock_path = os.path.join(out_dir, ".build.lock")
        with open(lock_path, "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            subprocess.run(["make", "-s"], cwd=_SRC_DIR, check=True,
                           capture_output=True, timeout=120)
        return os.path.exists(_lib_path())
    except Exception as e:
        logger.warning("native store build failed: %r", e)
        return False


def load_library():
    """Load (building if needed) the native library; None if unavailable.
    A failed build or load is cached for the process lifetime."""
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed:
        return None
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_failed:
            return None
        path = _lib_path()
        stale = False
        # Only the default build target is rebuilt on staleness; an
        # RTPU_STORE_LIB override (sanitizer variants) is built explicitly by
        # its own make target, so a stale check against it would rebuild the
        # wrong artifact and load the stale override anyway.
        if os.path.exists(path) and not flags.get("RTPU_STORE_LIB"):
            try:
                src = os.path.join(_SRC_DIR, "rtpu_store.cpp")
                stale = os.path.getmtime(src) > os.path.getmtime(path)
            except OSError:
                pass
        if (not os.path.exists(path) or stale) and not _build() \
                and not os.path.exists(path):
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(_lib_path())
        except OSError as e:
            # A .so that exists but won't dlopen is a stale artifact from a
            # different environment (e.g. built against a glibc where
            # shm_open didn't need -lrt). Rebuild in-tree once from
            # src/store and retry; no toolchain -> graceful skip as before.
            logger.warning("native store load failed: %r; rebuilding", e)
            try:
                os.unlink(path)
            except OSError:
                pass
            if flags.get("RTPU_STORE_LIB") or not _build():
                _lib_failed = True
                return None
            try:
                lib = ctypes.CDLL(_lib_path())
            except OSError as e2:
                logger.warning("native store rebuild still fails: %r", e2)
                _lib_failed = True
                return None
        lib.rtpu_store_create.restype = ctypes.c_void_p
        lib.rtpu_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_store_attach.restype = ctypes.c_void_p
        lib.rtpu_store_attach.argtypes = [ctypes.c_char_p]
        lib.rtpu_store_base.restype = ctypes.c_void_p
        lib.rtpu_store_base.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_alloc.restype = ctypes.c_uint64
        lib.rtpu_store_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                         ctypes.c_uint64]
        lib.rtpu_store_seal.restype = ctypes.c_int
        lib.rtpu_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_store_get.restype = ctypes.c_uint64
        lib.rtpu_store_get.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                       ctypes.POINTER(ctypes.c_uint64)]
        lib.rtpu_store_release.restype = ctypes.c_int
        lib.rtpu_store_release.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_store_delete.restype = ctypes.c_int
        lib.rtpu_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                          ctypes.c_int]
        lib.rtpu_store_contains.restype = ctypes.c_int
        lib.rtpu_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_store_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
        lib.rtpu_store_detach.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_unlink.restype = ctypes.c_int
        lib.rtpu_store_unlink.argtypes = [ctypes.c_char_p]
        try:
            lib.rtpu_memcpy_mt.restype = None
            lib.rtpu_memcpy_mt.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                           ctypes.c_uint64, ctypes.c_int]
        except AttributeError:
            pass  # stale pre-built .so without the symbol; fast_copy degrades
        _lib = lib
        return _lib


# Below this size a plain memoryview slice assignment beats the ctypes call
# overhead + thread spawn; above it the GIL-released multi-thread copy wins
# (one core sustains ~3.5 GB/s into the arena, the DRAM envelope is >2x that).
# Must track the single-thread short-circuit in rtpu_memcpy_mt (4ULL << 20):
# lowering only this constant routes 1-4MB payloads through a ctypes call
# that degenerates to plain memcpy.
FAST_COPY_MIN = 4 << 20


def fast_copy(dst_view: memoryview, dst_off: int, src) -> bool:
    """memcpy `src` (any buffer) into dst_view[dst_off:] via the native
    multi-threaded copy. Returns False (caller slice-assigns) when the
    payload is below FAST_COPY_MIN or the native library, symbol, or numpy
    is unavailable — the threshold lives HERE so call sites are just
    `if not fast_copy(...): view[a:b] = raw`."""
    try:
        n = memoryview(src).nbytes
    except TypeError:
        return False
    if n < FAST_COPY_MIN or not flags.get("RTPU_NATIVE_STORE"):
        return False
    lib = load_library()
    if lib is None or not hasattr(lib, "rtpu_memcpy_mt"):
        return False
    try:
        import numpy as np
    except ImportError:
        return False

    s = np.frombuffer(src, dtype=np.uint8)
    d = np.frombuffer(dst_view, dtype=np.uint8)
    if dst_off + s.nbytes > d.nbytes:
        raise ValueError("fast_copy out of bounds")
    lib.rtpu_memcpy_mt(d.ctypes.data + dst_off, s.ctypes.data, s.nbytes, 0)
    return True


class NativeArena:
    """One mapped arena (create or attach)."""

    def __init__(self, name: str, handle: int, lib, owner: bool):
        self.name = name
        self._h = handle
        self._lib = lib
        self._owner = owner
        self._base = lib.rtpu_store_base(handle)
        self._lock = threading.Lock()

    # ------------------------------------------------------------- factory

    @classmethod
    def create(cls, name: str, size: int) -> Optional["NativeArena"]:
        lib = load_library()
        if lib is None:
            return None
        h = lib.rtpu_store_create(name.encode(), size)
        if not h:
            return None
        return cls(name, h, lib, owner=True)

    @classmethod
    def attach(cls, name: str) -> Optional["NativeArena"]:
        lib = load_library()
        if lib is None:
            return None
        h = lib.rtpu_store_attach(name.encode())
        if not h:
            return None
        return cls(name, h, lib, owner=False)

    # ------------------------------------------------------------- objects

    def create_object(self, oid: int, size: int) -> Optional[memoryview]:
        """Writable view of a newly allocated (unsealed) object."""
        off = self._lib.rtpu_store_alloc(self._h, oid, size)
        if not off:
            return None
        buf = (ctypes.c_char * size).from_address(self._base + off)
        return memoryview(buf).cast("B")

    def seal(self, oid: int) -> bool:
        return self._lib.rtpu_store_seal(self._h, oid) == 0

    def get(self, oid: int) -> Optional[memoryview]:
        """Read view of a sealed object; pins it until release(oid)."""
        if not self._h:
            return None
        size = ctypes.c_uint64()
        off = self._lib.rtpu_store_get(self._h, oid, ctypes.byref(size))
        if not off:
            return None
        buf = (ctypes.c_char * size.value).from_address(self._base + off)
        return memoryview(buf).cast("B")

    def release(self, oid: int) -> None:
        if self._h:  # guard: detach() during shutdown NULLs the handle
            self._lib.rtpu_store_release(self._h, oid)

    def delete(self, oid: int, force: bool = False) -> bool:
        if not self._h:
            return False
        return self._lib.rtpu_store_delete(self._h, oid, int(force)) == 0

    def contains(self, oid: int) -> bool:
        return bool(self._h) and bool(self._lib.rtpu_store_contains(self._h, oid))

    def stats(self) -> Dict[str, int]:
        used = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        n = ctypes.c_uint64()
        self._lib.rtpu_store_stats(self._h, ctypes.byref(used),
                                   ctypes.byref(cap), ctypes.byref(n))
        return {"used": used.value, "capacity": cap.value,
                "num_objects": n.value}

    # ------------------------------------------------------------ lifetime

    def detach(self) -> None:
        if self._h:
            self._lib.rtpu_store_detach(self._h)
            self._h = 0

    def destroy(self) -> None:
        """Unmap and unlink the arena (owner/controller only)."""
        name = self.name
        self.detach()
        try:
            self._lib.rtpu_store_unlink(name.encode())
        except Exception:
            pass
        _unregister_arena(name)


# ------------------------------------------------------- per-process state

_arena: Optional[NativeArena] = None
_attached: Dict[str, NativeArena] = {}  # arenas attached by explicit name
_arena_state_lock = threading.Lock()
_ARENA_ENV = "RTPU_ARENA"
_ARENA_SIZE_ENV = "RTPU_ARENA_SIZE"
# Must track the RTPU_ARENA_SIZE registered default (flags.py): a smaller
# call-site fallback silently shrank every arena to 256MB, forcing large
# put working sets through the disk-spill path (round-4 put_gbps 1.4).
DEFAULT_ARENA_SIZE = 1 << 30


def arena_name_for_node(node_id: str) -> str:
    return f"/rtpu_arena_{node_id[:24]}"


_REGISTRY_DIR = "/tmp/rtpu_arenas"


def _register_arena(name: str) -> None:
    """Record creator pid so a later process can GC arenas whose creator was
    SIGKILLed (a hard-killed agent cannot unlink its own arena; the reference
    raylet has the same problem and relies on external cleanup)."""
    try:
        os.makedirs(_REGISTRY_DIR, exist_ok=True)
        with open(os.path.join(_REGISTRY_DIR, name.lstrip("/")), "w") as f:
            f.write(str(os.getpid()))
    except OSError:
        pass


def _unregister_arena(name: str) -> None:
    try:
        os.unlink(os.path.join(_REGISTRY_DIR, name.lstrip("/")))
    except OSError:
        pass


def gc_stale_arenas() -> int:
    """Unlink arenas whose creator process is dead. Runs at every arena
    create; returns the number reclaimed."""
    lib = load_library()
    if lib is None:
        return 0
    n = 0
    try:
        entries = os.listdir(_REGISTRY_DIR)
    except OSError:
        return 0
    for entry in entries:
        path = os.path.join(_REGISTRY_DIR, entry)
        try:
            with open(path) as f:
                pid = int(f.read().strip() or "0")
        except (OSError, ValueError):
            pid = 0
        alive = False
        if pid > 0:
            try:
                os.kill(pid, 0)
                alive = True
            except ProcessLookupError:
                alive = False
            except PermissionError:
                alive = True
        if not alive:
            try:
                lib.rtpu_store_unlink(("/" + entry).encode())
            except Exception:
                pass
            try:
                os.unlink(path)
            except OSError:
                pass
            n += 1
    return n


def create_node_arena(node_id: str) -> Optional[NativeArena]:
    """Controller-side: create this node's arena and advertise it via env
    (workers inherit the env at spawn)."""
    global _arena
    if not flags.get("RTPU_NATIVE_STORE"):
        return None
    with _arena_state_lock:
        if _arena is not None:
            return _arena
        gc_stale_arenas()
        size = flags.get("RTPU_ARENA_SIZE", default=DEFAULT_ARENA_SIZE)
        name = arena_name_for_node(node_id)
        arena = NativeArena.create(name, size)
        if arena is None:
            # Stale arena from a crashed run: unlink and retry once.
            lib = load_library()
            if lib is not None:
                lib.rtpu_store_unlink(name.encode())
                arena = NativeArena.create(name, size)
        if arena is not None:
            flags.set_env("RTPU_ARENA", name)
            _arena = arena
            _register_arena(name)
        return arena


def get_arena() -> Optional[NativeArena]:
    """Worker/driver-side: attach to the node's arena if advertised."""
    global _arena
    if _arena is not None:
        return _arena
    name = flags.get("RTPU_ARENA")
    if not name or not flags.get("RTPU_NATIVE_STORE"):
        return None
    with _arena_state_lock:
        if _arena is None:
            _arena = NativeArena.attach(name)
        return _arena


def attach_named(name: str) -> Optional[NativeArena]:
    """Attach (and cache) an arena by explicit shm name — the path for
    processes that didn't inherit RTPU_ARENA (e.g. a driver connecting to an
    existing cluster): the ObjectLocation carries the arena name."""
    with _arena_state_lock:
        if _arena is not None and _arena.name == name:
            return _arena
        a = _attached.get(name)
        if a is None:
            a = NativeArena.attach(name)
            if a is not None:
                _attached[name] = a
        return a


def close_arena(destroy: bool = False) -> None:
    global _arena
    with _arena_state_lock:
        if _arena is None:
            return
        if destroy:
            _arena.destroy()
        else:
            _arena.detach()
        _arena = None
        flags.unset_env("RTPU_ARENA")
