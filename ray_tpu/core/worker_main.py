"""Worker process entrypoint (reference:
python/ray/_private/workers/default_worker.py). Spawned by the controller with
RTPU_CONTROLLER / RTPU_NODE_ID in the environment."""
from __future__ import annotations

from ray_tpu import flags

import os
import sys


def main() -> int:
    addr = flags.get("RTPU_CONTROLLER")
    node_id = flags.get("RTPU_NODE_ID")
    if not addr or not node_id:
        sys.stderr.write("worker_main: RTPU_CONTROLLER / RTPU_NODE_ID not set\n")
        return 2
    extra_path = flags.get("RTPU_SYS_PATH")
    if extra_path:
        for p in reversed(extra_path.split(os.pathsep)):
            if p and p not in sys.path:
                sys.path.insert(0, p)
    from .worker import WorkerRuntime

    try:
        rt = WorkerRuntime(addr, node_id)
    except (ConnectionError, OSError):
        # Controller already gone (cluster shut down while we were spawning):
        # exit quietly, mirroring raylet workers dying with their raylet.
        return 0
    rt.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
