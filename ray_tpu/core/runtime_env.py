"""Runtime environments: per-task/actor working_dir, pip deps, env vars.

Parity: reference runtime-env system
(python/ray/_private/runtime_env/ARCHITECTURE.md, working_dir.py, pip.py)
redesigned for this control plane:

- ``working_dir``: the driver zips the directory (deterministic walk,
  junk excluded), content-hashes it, and uploads it to the controller KV
  under ``working_dir://<sha256>`` — at most once per content (URI cache,
  reference working_dir.py upload_package_if_needed). Workers download and
  extract once per host into a shared cache and run with cwd + sys.path
  pointing at it.
- ``pip``: the SPAWNER (controller or host agent — it is on the right
  host) materializes a venv per sorted-package-list hash
  (``--system-site-packages`` so the framework's own deps stay importable),
  installs the packages, and launches the worker with the venv's
  interpreter (reference pip.py creating virtualenvs keyed by spec hash).
- ``env_vars``: applied in the worker before user code runs.

An env's identity is the hash of all three parts; the scheduler only
dispatches a task to a worker with the same env hash (the reference keys
its worker pool the same way, worker_pool.h runtime_env_hash).
"""
from __future__ import annotations

from ray_tpu import flags

import hashlib
import io
import json
import os
import subprocess
import sys
import tempfile
import zipfile
from typing import Any, Dict, List, Optional

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules", ".eggs"}
_KV_NS = "__runtime_env__"


def _cache_root() -> str:
    d = flags.get("RTPU_RUNTIME_ENV_CACHE") or os.path.join(
        tempfile.gettempdir(), "rtpu_runtime_envs")
    os.makedirs(d, exist_ok=True)
    return d


# ----------------------------------------------------------------- normalize


def normalize(runtime_env: Optional[Dict[str, Any]], client) -> Optional[Dict[str, Any]]:
    """Driver-side: resolve a user runtime_env dict into its transportable
    form (working_dir replaced by a content URI, env hash computed) and
    upload the working_dir zip to the controller KV if new."""
    if not runtime_env:
        return None
    out: Dict[str, Any] = {}
    wd = runtime_env.get("working_dir")
    if wd:
        uri, blob = _package_working_dir(wd)
        # overwrite=False: the controller reports whether the URI was new —
        # unchanged directories upload exactly once (URI cache).
        client.request({"kind": "kv_put", "ns": _KV_NS, "key": uri,
                        "value": blob, "overwrite": False})
        out["working_dir_uri"] = uri
    py_modules = runtime_env.get("py_modules")
    if py_modules:
        # Each module (a local package dir or single .py file) ships as its
        # own content-addressed zip; workers extract each onto sys.path
        # WITHOUT chdir — the difference from working_dir (reference
        # _private/runtime_env/py_modules.py).
        uris = []
        for mod in py_modules:
            uri, blob = _package_py_module(str(mod))
            client.request({"kind": "kv_put", "ns": _KV_NS, "key": uri,
                            "value": blob, "overwrite": False})
            uris.append(uri)
        out["py_module_uris"] = uris
    pip = runtime_env.get("pip")
    if pip:
        out["pip"] = sorted(str(p) for p in pip)
    env_vars = runtime_env.get("env_vars")
    if env_vars:
        out["env_vars"] = {str(k): str(v) for k, v in env_vars.items()}
    conda = runtime_env.get("conda")
    if conda:
        if runtime_env.get("pip"):
            raise ValueError(
                "runtime_env cannot set both 'pip' and 'conda' (reference "
                "semantics: pip installs into the conda env via the conda "
                "spec's own pip section)")
        # str = existing named env; dict = environment.yml-style spec built
        # per content hash (reference _private/runtime_env/conda.py).
        out["conda"] = conda if isinstance(conda, str) else dict(conda)
    container = runtime_env.get("container")
    if container:
        if isinstance(container, str):  # common shorthand: just the image
            container = {"image": container}
        img = container.get("image")
        if not img:
            raise ValueError("runtime_env 'container' requires an 'image'")
        if runtime_env.get("pip") or runtime_env.get("conda"):
            raise ValueError(
                "runtime_env 'container' cannot combine with 'pip'/'conda' "
                "(reference semantics: the image brings its own "
                "environment)")
        out["container"] = {"image": str(img),
                            "run_options":
                                list(container.get("run_options") or ())}
    if not out:
        return None
    out["hash"] = env_hash(out)
    return out


def env_hash(norm: Dict[str, Any]) -> str:
    payload = json.dumps(
        {k: norm[k] for k in
         ("working_dir_uri", "py_module_uris", "pip", "env_vars",
          "conda", "container")
         if k in norm},
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def working_dir_fingerprint(path: str) -> str:
    """Cheap content fingerprint (relpath, size, mtime) of a directory —
    used to invalidate the driver-side normalization cache when files
    change without re-zipping on every submit."""
    path = os.path.abspath(path)
    h = hashlib.sha256()
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for f in sorted(files):
            if f.endswith(".pyc"):
                continue
            full = os.path.join(root, f)
            try:
                st = os.stat(full)
            except OSError:
                continue
            h.update(
                f"{os.path.relpath(full, path)}|{st.st_size}|{st.st_mtime_ns}"
                .encode())
    return h.hexdigest()[:16]


def _zip_tree(z: "zipfile.ZipFile", path: str, prefix: str,
              max_bytes: int, what: str) -> None:
    """Deterministic walk of `path` into the open zip under `prefix`,
    enforcing the shared size cap (one implementation for working_dir and
    py_modules — the cap exists to keep multi-GB checkpoints out of the
    controller KV)."""
    total = 0
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for f in sorted(files):
            if f.endswith(".pyc"):
                continue
            full = os.path.join(root, f)
            try:
                total += os.path.getsize(full)
            except OSError:
                pass
            if total > max_bytes:
                raise ValueError(
                    f"{what} {path!r} exceeds "
                    f"{max_bytes // (1024 * 1024)}MiB "
                    f"(reference default cap); exclude data/checkpoint "
                    f"files or raise RTPU_WORKING_DIR_MAX_BYTES")
            rel = os.path.join(prefix, os.path.relpath(full, path)) \
                if prefix else os.path.relpath(full, path)
            # Fixed date_time => identical content hashes to identical zips.
            info = zipfile.ZipInfo(rel, date_time=(2020, 1, 1, 0, 0, 0))
            with open(full, "rb") as fh:
                z.writestr(info, fh.read())


def _package_working_dir(path: str):
    """Zip `path` deterministically; return (content URI, zip bytes)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"working_dir {path!r} is not a directory")
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        _zip_tree(z, path, "", flags.get("RTPU_WORKING_DIR_MAX_BYTES"),
                  "working_dir")
    blob = buf.getvalue()
    digest = hashlib.sha256(blob).hexdigest()[:24]
    return f"working_dir://{digest}", blob


def _package_py_module(path: str):
    """Zip one python module (package dir or single .py) so extraction
    yields an importable top-level name; returns (content URI, zip bytes).
    Reference: _private/runtime_env/py_modules.py."""
    path = os.path.abspath(path)
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        if os.path.isdir(path):
            _zip_tree(z, path, os.path.basename(path.rstrip(os.sep)),
                      flags.get("RTPU_WORKING_DIR_MAX_BYTES"), "py_module")
        elif path.endswith(".py"):
            info = zipfile.ZipInfo(os.path.basename(path),
                                   date_time=(2020, 1, 1, 0, 0, 0))
            with open(path, "rb") as fh:
                z.writestr(info, fh.read())
        else:
            raise ValueError(
                f"py_modules entry {path!r} is neither a package directory "
                f"nor a .py file")
    blob = buf.getvalue()
    digest = hashlib.sha256(blob).hexdigest()[:24]
    return f"py_module://{digest}", blob


# ------------------------------------------------------------- worker side


def apply_in_worker(norm: Dict[str, Any], client) -> None:
    """Apply env_vars + working_dir in a freshly spawned worker (before user
    code loads). The pip part was already satisfied by the spawner: this
    interpreter IS the venv's when pip was requested."""
    for k, v in (norm.get("env_vars") or {}).items():
        flags.set_raw(k, v)
    for mod_uri in (norm.get("py_module_uris") or ()):
        target = _fetch_and_extract(mod_uri, client)
        # py_modules join sys.path WITHOUT chdir (the working_dir
        # difference): user code imports them from wherever it runs.
        if target not in sys.path:
            sys.path.insert(0, target)
    uri = norm.get("working_dir_uri")
    if uri:
        target = _fetch_and_extract(uri, client)
        os.chdir(target)
        if target not in sys.path:
            sys.path.insert(0, target)


def _fetch_and_extract(uri: str, client) -> str:
    """Download a content-addressed package from the controller KV and
    extract it into the local cache exactly once (ready-marker + rename
    race discipline); returns the extraction dir."""
    target = os.path.join(_cache_root(), uri.split("://", 1)[1])
    marker = os.path.join(target, ".rtpu_ready")
    if not os.path.exists(marker):
        blob = client.request({"kind": "kv_get", "ns": _KV_NS, "key": uri})
        if blob is None:
            raise RuntimeError(f"runtime env package {uri} missing from KV")
        tmp = target + f".tmp{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(blob)) as z:
            z.extractall(tmp)
        open(os.path.join(tmp, ".rtpu_ready"), "w").close()
        try:
            os.rename(tmp, target)
        except OSError:
            # Another worker won the race; its extraction is complete.
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    return target


# ------------------------------------------------------------ spawner side


import threading as _threading

_pip_env_lock = _threading.Lock()


def ensure_pip_env(pip: List[str]) -> str:
    """Materialize (or reuse) a venv with `pip` installed; returns its
    python executable. Cached per sorted-package-list hash. Builds are
    serialized in-process: concurrent spawns for the same env must not race
    one tmp dir into a half-installed venv."""
    import uuid

    key = hashlib.sha256(json.dumps(sorted(pip)).encode()).hexdigest()[:16]
    root = os.path.join(_cache_root(), f"pip_{key}")
    py = os.path.join(root, "bin", "python")
    marker = os.path.join(root, ".rtpu_ready")
    if os.path.exists(marker):
        return py
    with _pip_env_lock:
        if os.path.exists(marker):  # built while we waited
            return py
        return _build_pip_env(pip, root, py, uuid.uuid4().hex[:8])


def _build_pip_env(pip: List[str], root: str, py: str, tag: str) -> str:
    tmp = root + f".tmp{tag}"
    import venv

    venv.EnvBuilder(system_site_packages=True, with_pip=True).create(tmp)
    tmp_py = os.path.join(tmp, "bin", "python")
    # When this process itself runs in a venv, system_site_packages chains
    # to the BASE interpreter, skipping the parent venv's site-packages
    # (where e.g. setuptools lives). Chain them explicitly so the child env
    # sees everything the spawner could import.
    import site as _site

    parent_sites = [p for p in _site.getsitepackages() if os.path.isdir(p)]
    child_sites = [
        os.path.join(tmp, "lib", d, "site-packages")
        for d in os.listdir(os.path.join(tmp, "lib"))
    ]
    for cs in child_sites:
        if os.path.isdir(cs):
            with open(os.path.join(cs, "rtpu_parent.pth"), "w") as f:
                f.write("\n".join(parent_sites) + "\n")
    # --no-build-isolation: build against the venv's (system) setuptools
    # rather than fetching build deps — this framework targets zero-egress
    # TPU pods where only local/pre-mirrored packages install anyway.
    subprocess.run(
        [tmp_py, "-m", "pip", "install", "--no-input",
         "--no-build-isolation", *pip],
        check=True, capture_output=True, timeout=600,
    )
    # venv scripts embed the build path: relocate by rebuilding the pyvenv
    # prefix is unnecessary since we exec `bin/python -m`, which resolves
    # through the symlinked interpreter regardless of the directory name.
    open(os.path.join(tmp, ".rtpu_ready"), "w").close()
    try:
        os.rename(tmp, root)
    except OSError:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return py


_conda_env_lock = _threading.Lock()


def ensure_conda_env(spec) -> str:
    """Python interpreter for a conda runtime env (reference:
    _private/runtime_env/conda.py). A str names an existing env; a dict is
    an environment.yml-style spec materialized per content hash via
    ``conda env create`` and cached like pip envs. Requires a ``conda``
    binary on PATH (gated: zero-egress TPU pod images often ship without
    one — the error says so instead of failing mid-spawn)."""
    import shutil as _shutil

    conda = _shutil.which("conda")
    if conda is None:
        raise RuntimeError(
            "runtime_env requested a conda env but no 'conda' binary is on "
            "PATH; install conda/miniconda on every node or use the 'pip' "
            "runtime env instead")
    if isinstance(spec, str):
        out = subprocess.run(
            [conda, "run", "-n", spec, "python", "-c",
             "import sys; print(sys.executable)"],
            capture_output=True, text=True, timeout=120)
        if out.returncode != 0:
            raise RuntimeError(
                f"conda env {spec!r} not usable: {out.stderr[-300:]}")
        return out.stdout.strip().splitlines()[-1]
    key = hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]
    root = os.path.join(_cache_root(), f"conda_{key}")
    py = os.path.join(root, "bin", "python")
    marker = os.path.join(root, ".rtpu_ready")
    if os.path.exists(marker):
        return py
    with _conda_env_lock:
        if os.path.exists(marker):
            return py
        import uuid as _uuid

        tmp = root + f".tmp{_uuid.uuid4().hex[:8]}"
        # JSON is valid YAML: no PyYAML dependency needed for the spec file.
        with tempfile.NamedTemporaryFile(
                "w", suffix=".yml", delete=False) as f:
            json.dump(spec, f)
            spec_file = f.name
        try:
            # Build into a tmp prefix, rename when complete: a failed/
            # interrupted create must not poison the cache entry (conda
            # refuses to create into an existing prefix), and the atomic
            # rename also covers cross-process races the in-process lock
            # cannot (same pattern as _build_pip_env).
            subprocess.run(
                [conda, "env", "create", "-p", tmp, "-f", spec_file],
                check=True, capture_output=True, timeout=1800)
        except subprocess.CalledProcessError as e:
            import shutil as _shutil

            _shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"conda env create failed: "
                f"{(e.stderr or b'').decode()[-500:]}") from e
        finally:
            os.unlink(spec_file)
        open(os.path.join(tmp, ".rtpu_ready"), "w").close()
        try:
            os.rename(tmp, root)
        except OSError:
            import shutil as _shutil

            _shutil.rmtree(tmp, ignore_errors=True)
        return py


def container_command(norm: Dict[str, Any], worker_cmd: List[str],
                      *, runtime: Optional[str] = None) -> List[str]:
    """Wrap a worker launch command for container isolation (reference:
    _private/runtime_env/container.py worker-in-podman). The runtime
    binary comes from RTPU_CONTAINER_RUNTIME; host networking + the env
    cache mount keep the control plane and runtime-env caches reachable
    from inside."""
    from ray_tpu import flags

    runtime = runtime or flags.get("RTPU_CONTAINER_RUNTIME")
    c = norm["container"]
    cache = _cache_root()
    return [
        runtime, "run", "--rm", "--network=host",
        "-v", f"{cache}:{cache}",
        "-v", "/dev/shm:/dev/shm",
        *c.get("run_options", ()),
        c["image"], *worker_cmd,
    ]


def spawner_python(norm: Optional[Dict[str, Any]]) -> str:
    """Interpreter to launch a worker with for this runtime env."""
    if norm and norm.get("conda"):
        return ensure_conda_env(norm["conda"])
    if norm and norm.get("pip"):
        try:
            return ensure_pip_env(norm["pip"])
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"pip runtime env install failed: "
                f"{(e.stderr or b'').decode()[-500:]}"
            ) from e
    return sys.executable
