"""Host-side object store: the data plane for task/actor results.

Role-equivalent to the reference's plasma store + in-process memory store
(ray: src/ray/object_manager/plasma/, src/ray/core_worker/store_provider/),
redesigned for the TPU-host setting:

- Small objects (<= INLINE_THRESHOLD pickled bytes) are "inlined": their bytes
  travel on the control plane and live in the controller's memory store. This
  matches the reference's in-process store for small returns.
- Large objects are written once into POSIX shared memory by the producing
  process and read zero-copy-attached by any consumer process on the same
  host. Only the (shm name, size) location travels on the control plane.
- Device arrays: jax.Array values are pulled to host (numpy) at `put` time by
  the serializer. The TPU-native fast path for device-to-device movement is
  NOT this store — it is the mesh/collective layer (ray_tpu.parallel), where
  XLA moves bytes over ICI. The store moves *references and host bytes*,
  mirroring SURVEY.md §2.1's mapping note.

Pickling uses protocol 5 with out-of-band buffers so numpy arrays are
serialized without an intermediate copy of the payload bytes: buffers are
memcpy'd directly into the shared-memory segment.
"""
from __future__ import annotations

from ray_tpu import flags

import os
import pickle
import secrets
from dataclasses import dataclass, field
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Dict, List, Optional, Tuple

INLINE_THRESHOLD = 256 * 1024

_HDR = 8  # u64 little-endian length of the pickle stream, then buffer table

_machine_id_cache: Optional[str] = None


def current_host_id() -> str:
    """Identity of the host this process runs on, for same-host detection.

    Processes with equal host ids share POSIX shm (arena / per-object
    segments); differing ids force the inter-node transfer path
    (core.transfer). RTPU_HOST_ID overrides the machine identity so tests can
    simulate a remote host on one machine — the bytes then really stream over
    TCP via the host agent (reference: node_manager's object manager serving
    Push/Pull, src/ray/object_manager/object_manager.h).
    """
    env = flags.get("RTPU_HOST_ID")
    if env:
        return env
    global _machine_id_cache
    if _machine_id_cache is None:
        mid = None
        try:
            with open("/etc/machine-id") as f:
                mid = f.read().strip()
        except OSError:
            pass
        if not mid:
            import socket

            mid = socket.gethostname()
        _machine_id_cache = mid
    return _machine_id_cache


def _untrack(name: str) -> None:
    """Opt out of multiprocessing's resource tracker.

    Segment lifetime is owned by the controller (freed on explicit free or at
    cluster shutdown), not by whichever process happened to touch it first.
    """
    try:
        resource_tracker.unregister("/" + name, "shared_memory")
    except Exception:
        pass


class ArenaAttachError(RuntimeError):
    """This process cannot map the arena holding an object (library/layout
    skew, or the arena's creator host is gone). Distinct from RuntimeError so
    the retry path in get_bytes_with_refresh never swallows user-level
    RuntimeErrors raised during deserialization."""


@dataclass
class ObjectLocation:
    """Where an object's bytes live. Exactly one of `inline` / `shm_name` /
    `arena` is set."""

    object_id: str
    size: int
    inline: Optional[bytes] = None
    shm_name: Optional[str] = None
    node_id: Optional[str] = None
    is_error: bool = False
    # Buffer table for out-of-band pickle5 buffers: (offset, length) pairs,
    # relative to the object's data region.
    buffers: List[Tuple[int, int]] = field(default_factory=list)
    # Offset of the pickle stream inside the segment / arena object.
    pickle_off: int = 0
    pickle_len: int = 0
    # Native arena placement (C++ store, native_store.py): the arena's shm
    # name + the object's 64-bit id within it.
    arena: Optional[str] = None
    arena_oid: int = 0
    # Host identity of the producing process (current_host_id()); a reader on
    # a different host fetches via the owner node's agent instead of shm.
    host_id: Optional[str] = None
    # Spilled-to-disk placement (reference: raylet local_object_manager
    # spill, local_object_manager.h:103-122): same byte layout as the arena
    # object, in a file.
    spill_path: Optional[str] = None
    # "host:port" of the producing process's own pull server (its direct /
    # ref channel): consumers on another host try the producer first and
    # fall back to the host agent when it is gone (Ray's plasma/pull-manager
    # split — the controller keeps location metadata only).
    serve_addr: Optional[str] = None
    # Extra full copies of the same bytes on other hosts (broadcast
    # replicas). Attached by the controller on get_locations responses so a
    # consumer can fan one pull across several source hosts; never set on
    # stored locations.
    replicas: List["ObjectLocation"] = field(default_factory=list)


def serialize(value: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """Pickle with out-of-band buffers (protocol 5)."""
    oob: List[pickle.PickleBuffer] = []
    data = pickle.dumps(value, protocol=5, buffer_callback=oob.append)
    return data, oob


def put_bytes(value: Any, object_id: str, node_id: str) -> ObjectLocation:
    """Serialize `value`; inline small results, spill large ones to the
    native arena (preferred) or a per-object shm segment (fallback)."""
    from . import ownership
    from .serialization import capture_nested_refs

    # Refs nested in the payload are pinned by this process so the stored
    # bytes never outlive the objects they reference (ownership module
    # docstring: v1 pins for the process lifetime — safe direction).
    nested: list = []
    with capture_nested_refs(nested):
        data, oob = serialize(value)
    if nested:
        ownership.pin_nested(object_id, list(nested))
    total = len(data) + sum(len(b.raw()) for b in oob)
    if total <= INLINE_THRESHOLD or flags.get("RTPU_FORCE_INLINE"):
        # Re-pickle in-band: cheap at this size, keeps the inline path simple.
        # RTPU_FORCE_INLINE covers processes with no pull-server on their host
        # (a driver connected to a remote cluster): shm there is unreachable
        # by every consumer, so bytes must ride the control plane.
        if oob:
            data = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        return ObjectLocation(object_id=object_id, size=len(data), inline=data, node_id=node_id)

    serve_addr = _self_serve_addr()
    loc = _put_arena(data, oob, total, object_id, node_id)
    if loc is not None:
        loc.serve_addr = serve_addr
        return loc
    from . import native_store

    if native_store.get_arena() is not None:
        # Arena exists but is full: overflow to disk so working sets larger
        # than the arena complete instead of exhausting shm (reference:
        # local_object_manager spill-on-OOM). Disk latency is the natural
        # backpressure on the putting task.
        loc = _put_spill(data, oob, total, object_id, node_id)
        if loc is not None:
            loc.serve_addr = serve_addr
            return loc

    # Layout: [pickle stream][buf0][buf1]... with a location-table in metadata.
    name = "rtpu_" + secrets.token_hex(8)
    seg = shared_memory.SharedMemory(name=name, create=True, size=max(total, 1))
    _untrack(name)
    off = 0
    seg.buf[off : off + len(data)] = data
    pickle_off, pickle_len = off, len(data)
    off += len(data)
    table: List[Tuple[int, int]] = []
    for b in oob:
        raw = b.raw()
        n = raw.nbytes
        if not native_store.fast_copy(seg.buf, off, raw):
            seg.buf[off : off + n] = raw
        table.append((off, n))
        off += n
        b.release()
    loc = ObjectLocation(
        object_id=object_id,
        size=total,
        shm_name=name,
        node_id=node_id,
        buffers=table,
        pickle_off=pickle_off,
        pickle_len=pickle_len,
        host_id=current_host_id(),
        serve_addr=serve_addr,
    )
    seg.close()
    return loc


def _self_serve_addr() -> Optional[str]:
    """This process's own pull-serving "host:port" (its direct/ref server),
    stamped into produced locations so cross-host consumers can pull from
    the producer without a host-agent hop. None outside a live session or
    when worker-serving is disabled."""
    if not flags.get("RTPU_WORKER_SERVE"):
        return None
    from . import context as ctx

    if not ctx.is_initialized():
        return None
    from . import ownership

    addr = ownership.self_addr()
    if not addr:
        return None
    return addr.partition("|")[0]


def _arena_oid(object_id: str) -> int:
    oid = int(object_id[:15], 16) if object_id else 0
    return oid or 1


def _put_arena(data, oob, total, object_id, node_id) -> Optional[ObjectLocation]:
    """Write into the node's native arena; None -> caller falls back."""
    from . import native_store

    arena = native_store.get_arena()
    if arena is None:
        return None
    oid = _arena_oid(object_id)
    view = arena.create_object(oid, total)
    if view is None:  # arena OOM / oid collision
        return None
    off = 0
    view[off:off + len(data)] = data
    pickle_off, pickle_len = off, len(data)
    off += len(data)
    table: List[Tuple[int, int]] = []
    for b in oob:
        raw = b.raw()
        n = raw.nbytes
        # Large payloads (numpy/arrow buffers) go through the native
        # multi-threaded memcpy: the ctypes call releases the GIL and splits
        # the copy across cores, lifting the put path from one core's ~3.5
        # GB/s to the DRAM envelope (plasma parity: client-side write into
        # mapped store memory, src/ray/object_manager/plasma/client.cc).
        if not native_store.fast_copy(view, off, raw):
            view[off:off + n] = raw
        table.append((off, n))
        off += n
        b.release()
    del view
    arena.seal(oid)
    return ObjectLocation(
        object_id=object_id, size=total, node_id=node_id,
        buffers=table, pickle_off=pickle_off, pickle_len=pickle_len,
        arena=arena.name, arena_oid=oid, host_id=current_host_id())


def spill_dir() -> str:
    d = flags.get("RTPU_SPILL_DIR")
    if not d:
        import tempfile

        d = os.path.join(tempfile.gettempdir(),
                         f"rtpu_spill_{current_host_id()[:16]}")
    os.makedirs(d, exist_ok=True)
    return d


def spill_stats() -> Dict[str, int]:
    """Host-wide spill usage {files, bytes}: a directory scan (not a
    per-process counter) because every process on the host spills into the
    shared per-host dir — the census and the `rtpu status` object-store
    column want ground truth for the node, not one process's view. The
    dir is NOT created on a pure read."""
    d = flags.get("RTPU_SPILL_DIR")
    if not d:
        import tempfile

        d = os.path.join(tempfile.gettempdir(),
                         f"rtpu_spill_{current_host_id()[:16]}")
    files = 0
    total = 0
    try:
        with os.scandir(d) as it:
            for ent in it:
                try:
                    if ent.is_file():
                        files += 1
                        total += ent.stat().st_size
                except OSError:
                    continue
    except OSError:
        pass
    return {"files": files, "bytes": total}


def _put_spill(data, oob, total, object_id, node_id) -> Optional[ObjectLocation]:
    """Write the object's bytes (same layout as the arena) to a spill file.

    Buffers are released only after the whole file lands: a mid-write
    failure must leave them intact so put_bytes' shm fallback can still
    serialize them (and must not leave a partial file behind).
    """
    path = os.path.join(spill_dir(), f"{object_id[:32]}.bin")
    try:
        with open(path, "wb") as f:
            f.write(data)
            pickle_off, pickle_len = 0, len(data)
            off = len(data)
            table: List[Tuple[int, int]] = []
            for b in oob:
                raw = b.raw()
                n = raw.nbytes
                f.write(raw)
                table.append((off, n))
                off += n
    except OSError:
        try:
            os.unlink(path)
        except OSError:
            pass
        return None
    for b in oob:
        b.release()
    return ObjectLocation(
        object_id=object_id, size=total, node_id=node_id,
        buffers=table, pickle_off=pickle_off, pickle_len=pickle_len,
        spill_path=path, host_id=current_host_id())


def _get_spilled(loc: ObjectLocation) -> Any:
    with open(loc.spill_path, "rb") as f:
        buf = f.read()
    data = buf[loc.pickle_off : loc.pickle_off + loc.pickle_len]
    mv = memoryview(buf)
    bufs = [mv[off : off + n] for off, n in loc.buffers]
    return pickle.loads(data, buffers=bufs)


class _Pin:
    """A shared-memory read pin released when the last consumer value dies.

    One _Pin per zero-copy get; every out-of-band buffer handed to pickle
    holds a strong reference, so the arena refcount drops (or the segment
    mapping closes) exactly when Python can no longer reach any view of the
    bytes — plasma's client-buffer lifetime contract, driven by GC instead
    of an explicit Release RPC. Release is idempotent: interpreter exit
    drains whatever pins GC has not collected yet (the refcount lives in
    shared memory, so process death alone cannot drop it).
    """

    __slots__ = ("_release", "_done", "__weakref__")

    def __init__(self, release) -> None:
        self._release = release
        self._done = False
        _live_pins.add(self)

    def release(self) -> None:
        if self._done:
            return
        self._done = True
        try:
            self._release()
        except Exception:
            pass  # arena may already be detached/unlinked at shutdown

    def __del__(self) -> None:
        self.release()


class PinnedBuffer:
    """Read-only buffer view that keeps a _Pin alive (PEP 688).

    numpy arrays reconstructed from pickle5 out-of-band buffers keep their
    buffer object as ``.base`` — so the array's lifetime transitively holds
    the pin, and mutation is blocked because the exported view is read-only
    (same contract as plasma: values from get() are immutable).

    ``__buffer__`` is honored from Python 3.12; on older interpreters use
    :func:`pinned_buffer`, which returns a numpy-array wrapper exporting the
    buffer protocol natively (same pin/read-only contract).
    """

    __slots__ = ("_mv", "_pin")

    def __init__(self, mv: memoryview, pin: _Pin) -> None:
        self._mv = mv.toreadonly()
        self._pin = pin

    def __buffer__(self, flags: int) -> memoryview:
        return self._mv

    def __len__(self) -> int:
        return self._mv.nbytes


def pinned_buffer(mv: memoryview, pin: _Pin):
    """Buffer-protocol export of a pinned shared-memory view.

    Python < 3.12 ignores ``__buffer__`` (PEP 688), so a plain PinnedBuffer
    is rejected by every real consumer (``np.frombuffer`` raised TypeError —
    the long-standing get()-path env failure). Instead: a ctypes array
    mapped over the view holds the pin as an instance attribute, and a
    read-only uint8 ndarray over it is what pickle5 hands to consumers.
    numpy's base-chain collapse keeps the MEMORY OWNER (the ctypes holder)
    alive, so every reconstructed array transitively holds the pin — the
    plasma buffer-lifetime contract — while staying immutable.
    """
    import ctypes

    try:
        import numpy as np
    except ImportError:
        return PinnedBuffer(mv, pin)
    if mv.readonly:
        # from_buffer needs a writable exporter; today every call site
        # passes writable shm/arena slices. PEP-688 fallback otherwise.
        return PinnedBuffer(mv, pin)
    holder = (ctypes.c_char * mv.nbytes).from_buffer(mv)
    holder._rtpu_pin = pin
    arr = np.frombuffer(holder, dtype=np.uint8)
    arr.flags.writeable = False
    return arr


import weakref

# Weak refs only: a pin stays alive through the PinnedBuffers that hold it,
# and this set lets the atexit hook drain stragglers.
_live_pins: "weakref.WeakSet[_Pin]" = weakref.WeakSet()


class _SegmentCache:
    """Per-process cache of attached read-only segments."""

    def __init__(self) -> None:
        self._segs: Dict[str, shared_memory.SharedMemory] = {}

    def attach(self, name: str) -> shared_memory.SharedMemory:
        seg = self._segs.get(name)
        if seg is None:
            # No _untrack here: on Python 3.12 attaching does not register
            # with the resource tracker; unregistering would make the tracker
            # daemon log KeyErrors at exit.
            seg = shared_memory.SharedMemory(name=name)
            self._segs[name] = seg
        return seg

    def drop(self, name: str) -> None:
        seg = self._segs.pop(name, None)
        if seg is not None:
            try:
                seg.close()
            except Exception:
                pass

    def close_all(self) -> None:
        for name in list(self._segs):
            self.drop(name)


_segments = _SegmentCache()


def get_bytes(loc: ObjectLocation, copy: bool = False) -> Any:
    """Reconstruct the value at `loc`.

    Default is ZERO-COPY (plasma get semantics, reference
    src/ray/object_manager/plasma/store.h): out-of-band numpy buffers alias
    the shared memory read-only, each holding a GC-driven pin (_Pin) so the
    storage outlives every view. ``copy=True`` materializes private copies
    — for consumers that must mutate results in place.
    """
    if loc.inline is not None:
        return pickle.loads(loc.inline)
    if loc.host_id is not None and loc.host_id != current_host_id():
        from .transfer import fetch_remote_value

        return fetch_remote_value(loc)
    if loc.spill_path is not None:
        return _get_spilled(loc)
    if loc.arena is not None:
        return _get_arena_bytes(loc, copy)
    assert loc.shm_name is not None
    seg = _segments.attach(loc.shm_name)
    data = bytes(seg.buf[loc.pickle_off : loc.pickle_off + loc.pickle_len])
    if copy or not loc.buffers:
        # bytearray: a copy exists to be mutated (bytes would reconstruct
        # read-only numpy arrays).
        bufs: List[Any] = [bytearray(seg.buf[off:off + n])
                           for off, n in loc.buffers]
    else:
        # The release closure holds the SharedMemory object so the mapping
        # stays alive even if the cache drops it (free_segment) while views
        # are exported; POSIX keeps unlinked memory valid until munmap.
        pin = _Pin(lambda seg=seg: None)
        bufs = [pinned_buffer(seg.buf[off:off + n], pin)
                for off, n in loc.buffers]
    return pickle.loads(data, buffers=bufs)


def _get_arena_bytes(loc: ObjectLocation, copy: bool) -> Any:
    from . import native_store

    arena = native_store.get_arena()
    if arena is None or (arena.name != loc.arena):
        # Didn't inherit RTPU_ARENA (driver attached to an existing
        # cluster): the location itself names the arena — attach directly.
        arena = native_store.attach_named(loc.arena)
    if arena is None:
        raise ArenaAttachError(
            f"object {loc.object_id} lives in arena {loc.arena!r} which this "
            f"process could not attach")
    view = arena.get(loc.arena_oid)  # takes a shared-memory read pin
    if view is None:
        raise KeyError(f"object {loc.object_id} missing from arena "
                       f"(freed under a zero-copy reader?)")
    data = bytes(view[loc.pickle_off:loc.pickle_off + loc.pickle_len])
    if copy or not loc.buffers:
        try:
            bufs: List[Any] = [bytearray(view[off:off + n])
                               for off, n in loc.buffers]
            return pickle.loads(data, buffers=bufs)
        finally:
            del view
            arena.release(loc.arena_oid)
    # Zero-copy: each buffer holds the pin; the arena read-pin drops when
    # the last aliasing value is garbage-collected (or at interpreter
    # exit via the atexit drain). The controller can still force-delete —
    # same contract as plasma.
    pin = _Pin(lambda a=arena, o=loc.arena_oid: a.release(o))
    bufs = [pinned_buffer(view[off:off + n], pin) for off, n in loc.buffers]
    return pickle.loads(data, buffers=bufs)


def _release_zero_copy_pins() -> None:
    for pin in list(_live_pins):
        pin.release()


import atexit as _atexit

_atexit.register(_release_zero_copy_pins)


def get_bytes_with_refresh(loc: ObjectLocation, object_id: str, request_fn):
    """get_bytes with a single location refresh when the copy moved — the
    arena object was spilled between resolution and the read (KeyError),
    or the cached location's HOST died and the pull failed
    (ConnectionError/OSError), or the local arena refused to attach
    (ArenaAttachError — e.g. a freshly rebuilt library with a bumped
    shm-layout stamp reading an arena created under the old layout; the
    refresh gives lineage reconstruction a chance to re-produce the object
    somewhere this process CAN read). The refresh timeout is long enough for
    lineage reconstruction to re-run the producer (the controller blocks
    the location request while the resubmitted task executes); if the
    object was freed outright the caller still gets a timely error."""
    try:
        return get_bytes(loc), loc
    except (KeyError, ConnectionError, OSError, TimeoutError,
            ArenaAttachError):
        locs = request_fn(
            {"kind": "get_locations", "object_ids": [object_id],
             "timeout": 30}
        )
        loc = locs[object_id]
        return get_bytes(loc), loc


def storage_kind(loc: ObjectLocation) -> str:
    """Canonical storage-backend label for observability surfaces (`rtpu
    memory`, the state API): exactly one place decides the name of each
    backend so the two views can never drift. The labels are EXTERNAL API
    (scripted `rtpu memory` / `list_objects()` consumers key on them) —
    'spill' is the original, published name; do not rename."""
    if loc.is_error:
        return "error"
    if loc.inline is not None:
        return "inline"
    if loc.spill_path:
        return "spill"
    if loc.arena:
        return "arena"
    if loc.shm_name:
        return "shm"
    return "?"


def free_location(loc: ObjectLocation) -> None:
    """Free an object's storage, whichever backend holds it."""
    if loc.spill_path is not None:
        try:
            os.unlink(loc.spill_path)
        except OSError:
            pass
        return
    if loc.arena is not None:
        from . import native_store

        arena = native_store.get_arena()
        if arena is None or arena.name != loc.arena:
            arena = native_store.attach_named(loc.arena)
        if arena is not None:
            arena.delete(loc.arena_oid)
        return
    if loc.shm_name:
        free_segment(loc.shm_name)


def free_segment(shm_name: str) -> None:
    """Unlink a segment (controller-driven).

    Uses shm_unlink directly: SharedMemory.unlink() would also ping the
    resource tracker, which never saw this name in the freeing process and
    would log KeyErrors from its daemon at exit.
    """
    _segments.drop(shm_name)
    try:
        import _posixshmem

        _posixshmem.shm_unlink("/" + shm_name)
    except FileNotFoundError:
        pass
    except Exception:
        pass


def close_process_segments() -> None:
    _segments.close_all()


# ---------------------------------------------- mutable channel slot rings
#
# The compiled-DAG channel substrate (reference: MutableObjectManager's
# mutable plasma objects backing aDAG channels). Unlike every object above,
# a slot ring is MUTABLE shared memory: one writer and up to MAX_READERS
# readers on the same host rendezvous on a fixed ring of slots, re-used for
# every execution, so the steady-state cost of moving a value between two
# processes is one memcpy + two 8-byte header stores — no allocation, no
# pickle of locations, no controller message. Layout (all u64, aligned):
#
#   [write_seq][closed][depth][slot_size][n_readers][writer_waiting]
#   [read_seq[0]][reader_waiting[0]] ... x MAX_READERS
#   one 64B writer counter line (items, bytes, blocked_ns)
#   MAX_READERS 64B reader counter lines (items, bytes, starved_ns)
#   then `depth` slots of (seq, kind, len) + slot_size payload bytes.
#
# The counter lines are the channel-observability substrate (RTPU_DAG_METER):
# the hot path does plain unsynchronized u64 read-modify-writes into its OWN
# cache line (single writer per field, same argument as the cursors), and an
# out-of-band sampler on the ring-hosting worker reads them at heartbeat
# cadence — occupancy and per-reader lag are derived from the existing
# cursors at sample time, costing the hot path nothing.
#
# Single-writer/multi-reader protocol: the writer fills slot seq%depth and
# THEN publishes by storing write_seq=seq+1; a reader consumes the slot and
# THEN stores its read_seq=seq+1. Aligned 8-byte stores are atomic on every
# platform we run on, and each field has exactly one writing process, so no
# locks exist anywhere on the hot path. A slot is reusable once every
# reader's read_seq has passed it (min_read_seq), which is what bounds the
# pipeline to `depth` in-flight items. The waiting flags let the peer skip
# the doorbell syscall when nobody is blocked (dag/channels.py owns the
# doorbells; this class is pure layout + accounting).

import struct as _struct
import threading as _threading

_U64 = _struct.Struct("<Q")
_SLOT_HDR = _struct.Struct("<QQQ")  # seq, kind, len

# Per-process accounting of OPEN channel segments (rings + sidecars): the
# chaos tests assert teardown leaks nothing by diffing this.
_channel_lock = _threading.Lock()
_channel_open: Dict[str, int] = {}  # name -> mapped bytes


def track_channel_segment(name: str, nbytes: int) -> None:
    with _channel_lock:
        _channel_open[name] = nbytes


def untrack_channel_segment(name: str) -> None:
    with _channel_lock:
        _channel_open.pop(name, None)


def channel_segment_stats() -> Dict[str, int]:
    """Open channel segments (slot rings + oversize sidecars) mapped by
    THIS process: {"segments": count, "bytes": total mapped}."""
    with _channel_lock:
        return {"segments": len(_channel_open),
                "bytes": sum(_channel_open.values())}


def host_channel_stats() -> Dict[str, int]:
    """Host-wide channel-fabric footprint {segments, bytes}: a /dev/shm
    scan for live ``rtpu_ch_*`` segments. Like spill_stats this is ground
    truth for the NODE (heartbeated by the host agent), not one process's
    mapped view — every process on the host creates rings in the same
    namespace, and a leaked ring from a dead writer still shows up here."""
    segs = 0
    total = 0
    try:
        for fn in os.listdir("/dev/shm"):
            if fn.startswith("rtpu_ch_"):
                try:
                    total += os.stat(os.path.join("/dev/shm", fn)).st_size
                except OSError:
                    continue
                segs += 1
    except OSError:
        pass  # non-Linux: no /dev/shm to scan
    return {"segments": segs, "bytes": total}


class SlotRing:
    """One mutable shm channel: a depth-bounded ring of fixed-size slots.

    Created by the producing process, attached by every consumer on the
    same host. `kind` is an application tag rode along with each item
    (dag/channels.py uses it for inline-pickle vs sidecar vs error)."""

    MAX_READERS = 8
    _RHDR = 64                       # fixed header bytes before reader table
    _CTR_OFF = _RHDR + 16 * MAX_READERS   # writer counter line
    _CTR_R_OFF = _CTR_OFF + 64            # per-reader counter lines
    _SLOTS_OFF = _CTR_R_OFF + 64 * MAX_READERS

    def __init__(self, seg: shared_memory.SharedMemory, created: bool):
        self._seg = seg
        self._created = created
        buf = seg.buf
        self.depth = _U64.unpack_from(buf, 16)[0]
        self.slot_size = _U64.unpack_from(buf, 24)[0]
        self.n_readers = _U64.unpack_from(buf, 32)[0]
        self._stride = _SLOT_HDR.size + self.slot_size
        # u64-cast view over the counter lines: `q[i] += d` is ~5x cheaper
        # than struct pack/unpack round-trips, and the counter bumps are
        # the only shm writes on the metered per-item hot path. mmap
        # rounds segments to page size, so the cast never fails on
        # alignment — the guard is for exotic buffer providers only.
        try:
            self._ctr_q = buf.cast("Q") if len(buf) % 8 == 0 else None
        except (TypeError, ValueError):
            self._ctr_q = None
        self._qw = self._CTR_OFF // 8       # writer counter line, q-index
        self._qr = self._CTR_R_OFF // 8     # reader counter lines, q-index
        track_channel_segment(seg.name, seg.size)

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def create(cls, depth: int, slot_size: int, n_readers: int,
               name: Optional[str] = None, epoch: int = 0, base: int = 0,
               reader_starts: Optional[List[int]] = None) -> "SlotRing":
        """`epoch`/`base`/`reader_starts` exist for DAG recovery: a rebuilt
        ring starts mid-stream (write_seq=base, each reader's cursor at the
        first seqno it still needs) under a bumped epoch so a stale cursor
        can never be satisfied by the wrong incarnation."""
        if n_readers > cls.MAX_READERS:
            raise ValueError(
                f"slot ring supports at most {cls.MAX_READERS} same-host "
                f"readers (got {n_readers})")
        depth = max(1, int(depth))
        total = cls._SLOTS_OFF + depth * (_SLOT_HDR.size + slot_size)
        name = name or ("rtpu_ch_" + secrets.token_hex(8))
        seg = shared_memory.SharedMemory(name=name, create=True, size=total)
        _untrack(name)
        seg.buf[:cls._SLOTS_OFF] = bytes(cls._SLOTS_OFF)
        _U64.pack_into(seg.buf, 0, base)
        _U64.pack_into(seg.buf, 24, slot_size)
        _U64.pack_into(seg.buf, 32, n_readers)
        _U64.pack_into(seg.buf, 48, epoch)
        for i in range(n_readers):
            start = base if reader_starts is None else reader_starts[i]
            _U64.pack_into(seg.buf, cls._RHDR + 16 * i, start)
        # depth is the attachers' readiness gate — publish it last so a
        # racing attach never observes cursors/epoch mid-initialization.
        _U64.pack_into(seg.buf, 16, depth)
        return cls(seg, created=True)

    @classmethod
    def attach(cls, name: str) -> "SlotRing":
        # _untrack: on 3.10 attaching registers with the resource tracker,
        # which would unlink the ring when the FIRST attacher exits; ring
        # lifetime belongs to the creating writer.
        seg = shared_memory.SharedMemory(name=name)
        _untrack(name)
        return cls(seg, created=False)

    @property
    def name(self) -> str:
        return self._seg.name

    def close(self) -> None:
        untrack_channel_segment(self._seg.name)
        if self._ctr_q is not None:
            # The cast view keeps an export on the mmap; release it or
            # SharedMemory.close() raises BufferError and leaks the map.
            try:
                self._ctr_q.release()
            except Exception:
                pass
            self._ctr_q = None
        try:
            self._seg.close()
        except Exception:
            pass

    def unlink(self) -> None:
        name = self._seg.name
        self.close()
        try:
            import _posixshmem

            _posixshmem.shm_unlink("/" + name)
        except Exception:
            pass

    # -- header fields (each has exactly one writing process) --------------
    def write_seq(self) -> int:
        return _U64.unpack_from(self._seg.buf, 0)[0]

    def closed(self) -> bool:
        return _U64.unpack_from(self._seg.buf, 8)[0] != 0

    def mark_closed(self) -> None:
        _U64.pack_into(self._seg.buf, 8, 1)

    def epoch(self) -> int:
        return _U64.unpack_from(self._seg.buf, 48)[0]

    def read_seq(self, idx: int) -> int:
        return _U64.unpack_from(self._seg.buf, self._RHDR + 16 * idx)[0]

    def set_read_seq(self, idx: int, seq: int) -> None:
        _U64.pack_into(self._seg.buf, self._RHDR + 16 * idx, seq)

    def min_read_seq(self) -> int:
        return min(self.read_seq(i) for i in range(self.n_readers))

    def writer_waiting(self) -> bool:
        return _U64.unpack_from(self._seg.buf, 40)[0] != 0

    def set_writer_waiting(self, v: bool) -> None:
        _U64.pack_into(self._seg.buf, 40, 1 if v else 0)

    def reader_waiting(self, idx: int) -> bool:
        off = self._RHDR + 16 * idx + 8
        return _U64.unpack_from(self._seg.buf, off)[0] != 0

    def set_reader_waiting(self, idx: int, v: bool) -> None:
        _U64.pack_into(self._seg.buf, self._RHDR + 16 * idx + 8,
                       1 if v else 0)

    # -- telemetry counter lines (RTPU_DAG_METER) --------------------------
    # Unsynchronized u64 read-modify-writes: each field has exactly one
    # writing process (the ring writer / reader idx), so the only hazard is
    # a sampler reading mid-update — which observes either the old or new
    # value, never a torn one (aligned 8-byte stores).

    def _bump(self, off: int, delta: int) -> None:
        buf = self._seg.buf
        _U64.pack_into(buf, off, _U64.unpack_from(buf, off)[0] + delta)

    def ctr_write(self, items: int, nbytes: int) -> None:
        q = self._ctr_q
        if q is not None:
            i = self._qw
            q[i] += items
            q[i + 1] += nbytes
            return
        self._bump(self._CTR_OFF, items)
        self._bump(self._CTR_OFF + 8, nbytes)

    def ctr_blocked(self, ns: int) -> None:
        q = self._ctr_q
        if q is not None:
            q[self._qw + 2] += ns
            return
        self._bump(self._CTR_OFF + 16, ns)

    def ctr_read(self, idx: int, items: int, nbytes: int) -> None:
        q = self._ctr_q
        if q is not None:
            i = self._qr + 8 * idx
            q[i] += items
            q[i + 1] += nbytes
            return
        off = self._CTR_R_OFF + 64 * idx
        self._bump(off, items)
        self._bump(off + 8, nbytes)

    def ctr_starved(self, idx: int, ns: int) -> None:
        q = self._ctr_q
        if q is not None:
            q[self._qr + 8 * idx + 2] += ns
            return
        self._bump(self._CTR_R_OFF + 64 * idx + 16, ns)

    def counters(self) -> Dict[str, Any]:
        """Sampler-side snapshot: cumulative writer/reader counters plus
        occupancy and per-reader lag derived from the live cursors."""
        buf = self._seg.buf
        w = self.write_seq()
        readers = []
        for i in range(self.n_readers):
            off = self._CTR_R_OFF + 64 * i
            readers.append({
                "items": _U64.unpack_from(buf, off)[0],
                "bytes": _U64.unpack_from(buf, off + 8)[0],
                "starved_ns": _U64.unpack_from(buf, off + 16)[0],
                "lag": w - self.read_seq(i),
            })
        return {
            "epoch": self.epoch(),
            "write_seq": w,
            "occupancy": w - self.min_read_seq(),
            "depth": self.depth,
            "items": _U64.unpack_from(buf, self._CTR_OFF)[0],
            "bytes": _U64.unpack_from(buf, self._CTR_OFF + 8)[0],
            "blocked_ns": _U64.unpack_from(buf, self._CTR_OFF + 16)[0],
            "readers": readers,
        }

    # -- writer side -------------------------------------------------------
    def has_space(self, seq: int) -> bool:
        return seq - self.min_read_seq() < self.depth

    def write(self, seq: int, kind: int, payload) -> None:
        """Fill slot seq%depth and publish it (write_seq := seq+1). The
        caller must hold has_space(seq); payload must fit slot_size."""
        n = memoryview(payload).nbytes
        if n > self.slot_size:
            raise ValueError(f"payload {n}B exceeds slot {self.slot_size}B")
        off = self._SLOTS_OFF + (seq % self.depth) * self._stride
        _SLOT_HDR.pack_into(self._seg.buf, off, seq, kind, n)
        self._seg.buf[off + _SLOT_HDR.size: off + _SLOT_HDR.size + n] = \
            payload
        _U64.pack_into(self._seg.buf, 0, seq + 1)  # publish

    # -- reader side -------------------------------------------------------
    def readable(self, idx: int) -> bool:
        return self.write_seq() > self.read_seq(idx)

    def read(self, idx: int) -> Tuple[int, int, bytes]:
        """Copy out the next item for reader idx WITHOUT advancing; the
        caller advances after it has finished with the bytes."""
        seq = self.read_seq(idx)
        off = self._SLOTS_OFF + (seq % self.depth) * self._stride
        sseq, kind, n = _SLOT_HDR.unpack_from(self._seg.buf, off)
        if sseq != seq:  # torn ring (writer died mid-slot / layout skew)
            raise RuntimeError(
                f"channel ring {self.name}: slot seq {sseq} != expected "
                f"{seq}")
        data = bytes(
            self._seg.buf[off + _SLOT_HDR.size: off + _SLOT_HDR.size + n])
        return seq, kind, data

    def advance(self, idx: int) -> None:
        _U64.pack_into(self._seg.buf, self._RHDR + 16 * idx,
                       self.read_seq(idx) + 1)
