"""Controller-side durable job table (reference: GcsJobManager + the
dashboard/modules/job JobManager driving one JobSupervisor actor per job).

The JobManager owns every job record — id, entrypoint, runtime env,
attempt accounting, status history, supervisor actor id, current
entrypoint process group — persisted in the --state-path snapshot so the
table (and an in-flight ``wait_job`` cursor) survives a controller
bounce. The per-job supervisor (ray_tpu/jobs.py) is a restartable
detached actor; it never decides anything about attempts itself: every
attempt starts with a ``job_attempt_start`` RPC here, which is where the
retry budget, the capped-exponential backoff, and the PR 4/16 convention
that preempted/drained deaths burn zero budget are enforced.

Attempt accounting model: ``attempt`` counts every launch of the
entrypoint (monotonic — the RTPU_JOB_ATTEMPT value), ``billed`` counts
only launches that consumed retry budget. A launch following a planned
departure (drain/preemption) is free; everything else — the first
launch, relaunch after a nonzero exit, relaunch after a supervisor
crash — bills one unit, and a billed launch that would exceed
``max_attempts`` fails the job instead of starting.
"""
from __future__ import annotations

import asyncio
import os
import signal
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import flags

# Mirrors jobs.JobStatus (jobs.py imports these — core must not import
# the driver-side API back).
PENDING = "PENDING"
RUNNING = "RUNNING"
RETRYING = "RETRYING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"
TERMINAL_STATES = frozenset({SUCCEEDED, FAILED, STOPPED})

# Actor-name prefix linking a supervisor actor back to its job record
# (the controller's actor-death hooks key off it).
SUPERVISOR_PREFIX = "_job:"
# Pubsub channel prefix the supervisor subscribes to for stop requests.
STOP_CHANNEL_PREFIX = "__job__:"

JOB_RUNTIME_BOUNDARIES = [1.0, 5.0, 15.0, 60.0, 300.0, 1800.0,
                          7200.0, 43200.0]

_HISTORY_MAX = 50


def stop_channel(job_id: str) -> str:
    return STOP_CHANNEL_PREFIX + job_id


def kill_process_group(pgid: int, grace_s: float = 3.0) -> bool:
    """Terminate→kill escalation of one process group, reaped bounded.

    The entrypoint runs in its own session (start_new_session=True), so
    this takes down shell=True children and detached grandchildren the
    old ``proc.terminate()`` leaked. Never signals pgid <= 1 or our own
    group. Returns True once the group is observably gone."""
    try:
        pgid = int(pgid)
    except (TypeError, ValueError):
        return False
    if pgid <= 1:
        return False
    try:
        if pgid == os.getpgrp():
            return False
    except OSError:
        pass
    try:
        os.killpg(pgid, signal.SIGTERM)
    except ProcessLookupError:
        return True
    except OSError:
        return False
    deadline = time.monotonic() + max(0.0, float(grace_s))
    while time.monotonic() < deadline:
        try:
            os.killpg(pgid, 0)
        except ProcessLookupError:
            return True
        except OSError:
            return False
        time.sleep(0.05)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except ProcessLookupError:
        return True
    except OSError:
        return False
    # Reap window: direct children are reaped by their Popen owner;
    # orphans reparent to init. Poll until the group is gone (bounded).
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        try:
            os.killpg(pgid, 0)
        except ProcessLookupError:
            return True
        except OSError:
            return False
        time.sleep(0.05)
    return False


class JobManager:
    """Job table + attempt protocol, living inside the controller."""

    def __init__(self, ctrl) -> None:
        self.ctrl = ctrl
        import collections

        # job_id -> record (plain dicts: they pickle into the state
        # snapshot as-is). Insertion-ordered for bounded eviction.
        self.jobs: "collections.OrderedDict[str, Dict[str, Any]]" = (
            collections.OrderedDict())
        # rtpu_job_attempts_total{cause} — persisted so the counter
        # never goes backwards across a controller bounce.
        self.attempt_counts: Dict[str, int] = {}
        # rtpu_job_runtime_s histogram state (terminal-job runtimes).
        self.runtime_hist: Dict[str, Any] = {
            "buckets": [0] * len(JOB_RUNTIME_BOUNDARIES),
            "sum": 0.0, "count": 0}
        self._waiters: Dict[str, List[asyncio.Event]] = {}
        self._gc_done = False

    # ------------------------------------------------------------ plumbing

    def _touch(self, rec: Dict[str, Any]) -> None:
        """Bump the record's wait_job cursor and wake long-pollers."""
        rec["seq"] = int(rec.get("seq", 0)) + 1
        self.ctrl._state_dirty = True
        for ev in self._waiters.pop(rec["job_id"], []):
            ev.set()

    async def _wait_change(self, job_id: str, timeout: float) -> None:
        ev = asyncio.Event()
        self._waiters.setdefault(job_id, []).append(ev)
        try:
            await asyncio.wait_for(ev.wait(), max(0.01, timeout))
        except asyncio.TimeoutError:
            pass
        finally:
            lst = self._waiters.get(job_id)
            if lst is not None and ev in lst:
                lst.remove(ev)

    def _set_status(self, rec: Dict[str, Any], status: str,
                    cause: Optional[str] = None) -> None:
        rec["status"] = status
        rec["history"].append({"status": status, "ts": time.time(),
                               "cause": cause})
        del rec["history"][:-_HISTORY_MAX]
        if status in TERMINAL_STATES:
            rec["finished_ts"] = time.time()
            if rec.get("started_ts"):
                self._observe_runtime(rec["finished_ts"]
                                      - rec["started_ts"])
        self._touch(rec)

    def _observe_runtime(self, runtime_s: float) -> None:
        h = self.runtime_hist
        for i, b in enumerate(JOB_RUNTIME_BOUNDARIES):
            if runtime_s <= b:
                h["buckets"][i] += 1
                break
        h["sum"] += runtime_s
        h["count"] += 1

    def _emit(self, severity: str, kind: str, message: str,
              rec: Dict[str, Any], **extra) -> None:
        data = dict(extra.pop("data", None) or {})
        data.setdefault("job_id", rec["job_id"])
        ex = rec.get("exec") or {}
        self.ctrl._emit_event(
            severity, kind, message,
            actor_id=rec.get("supervisor_actor_id"),
            node_id=extra.pop("node_id", None) or ex.get("node_id"),
            data=data, **extra)

    def _gc_legacy_kv(self) -> None:
        """Drop the pre-FT ``__jobs__`` KV rows (they rotted into
        status="DEAD", entrypoint="?" listings); the job table is the
        listing source of truth now."""
        if self._gc_done:
            return
        self._gc_done = True
        stale = [k for k in self.ctrl.kv if k[0] == "__jobs__"]
        for k in stale:
            self.ctrl.kv.pop(k, None)
        if stale:
            self.ctrl._state_dirty = True

    def _evict(self) -> None:
        cap = int(flags.get("RTPU_JOBS_MAX"))
        if len(self.jobs) <= cap:
            return
        for jid in [j for j, r in self.jobs.items()
                    if r["status"] in TERMINAL_STATES]:
            if len(self.jobs) <= cap:
                break
            self.jobs.pop(jid, None)
            self._waiters.pop(jid, None)

    def public(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        ex = rec.get("exec") or {}
        return {
            "job_id": rec["job_id"],
            "status": rec["status"],
            "entrypoint": rec["entrypoint"],
            "returncode": rec.get("returncode"),
            "attempt": rec.get("attempt", 0),
            "attempts_used": rec.get("billed", 0),
            "max_attempts": rec.get("max_attempts"),
            "message": rec.get("message"),
            "stop_requested": bool(rec.get("stop_requested")),
            "submitted_ts": rec.get("submitted_ts"),
            "started_ts": rec.get("started_ts"),
            "finished_ts": rec.get("finished_ts"),
            "node_id": ex.get("node_id"),
            "history": list(rec.get("history") or [])[-20:],
        }

    # ----------------------------------------------------------- lifecycle

    def submit(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        self._gc_legacy_kv()
        job_id = msg["job_id"]
        if job_id in self.jobs:
            # Idempotent: a driver retrying submit after a reconnect must
            # not reset a live record.
            return {"ok": True, "job_id": job_id,
                    "record": self.public(self.jobs[job_id])}
        rec: Dict[str, Any] = {
            "job_id": job_id,
            "entrypoint": msg.get("entrypoint") or "",
            "env_vars": dict(msg.get("env_vars") or {}),
            "working_dir": msg.get("working_dir"),
            "num_cpus": float(msg.get("num_cpus") or 1.0),
            "max_attempts": int(msg.get("max_attempts")
                                or flags.get("RTPU_JOB_MAX_ATTEMPTS")),
            "status": PENDING,
            "returncode": None,
            "message": None,
            "attempt": 0,
            "billed": 0,
            "supervisor_actor_id": None,
            "seq": 0,
            "history": [],
            "submitted_ts": time.time(),
            "started_ts": None,
            "finished_ts": None,
            "stop_requested": False,
            "pending_cause": None,
            "exec": None,
            "attempt_logs": [],
            "last_tail": "",
        }
        self.jobs[job_id] = rec
        self._set_status(rec, PENDING)
        self._emit("INFO", "JOB_SUBMITTED",
                   f"job {job_id} submitted: {rec['entrypoint'][:120]}",
                   rec, data={"entrypoint": rec["entrypoint"],
                              "max_attempts": rec["max_attempts"]})
        self._evict()
        return {"ok": True, "job_id": job_id, "record": self.public(rec)}

    async def attempt_start(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """A supervisor (fresh, restarted, or restored) asks to launch
        the entrypoint. The controller is the attempt journal: it decides
        run/stop/fail, bills the budget, computes the backoff, emits
        JOB_STARTED / exactly one JOB_RETRYING per attempt, and
        best-effort kills the previous attempt's orphaned process
        group."""
        job_id = msg.get("job_id") or ""
        rec = self.jobs.get(job_id)
        if rec is None:
            return {"action": "fail", "error": f"unknown job {job_id!r}"}
        if msg.get("actor_id"):
            rec["supervisor_actor_id"] = msg["actor_id"]
        if rec["status"] in TERMINAL_STATES:
            return {"action": "stop", "status": rec["status"]}
        if rec.get("stop_requested"):
            self._set_status(rec, STOPPED, cause="stop requested")
            self._emit("INFO", "JOB_STOPPED",
                       f"job {job_id} stopped before attempt "
                       f"{rec['attempt'] + 1} started", rec)
            return {"action": "stop", "status": STOPPED}
        # Orphan sweep: the previous attempt's process group survived its
        # supervisor (SIGKILLed worker, preempted node) — tear it down
        # before a replacement launches, so two attempts never overlap.
        prev = rec.get("exec")
        if prev and prev.get("pgid"):
            self._spawn_exec_kill(dict(prev))
        cause = rec.pop("pending_cause", None)
        if cause is None:
            if rec["attempt"] == 0:
                cause = {"cause": "initial", "detail": "first attempt",
                         "preempted": False}
            else:
                # Supervisor came back without the controller observing a
                # death (live drain-migration restores take this path when
                # the migration hook raced). Infer from the previous
                # placement.
                node = self.ctrl.nodes.get((prev or {}).get("node_id")
                                           or "")
                preempted = node is not None and (node.draining
                                                  or node.drained)
                cause = {"cause": "preempted" if preempted
                         else "supervisor_restart",
                         "detail": "supervisor restarted",
                         "preempted": preempted}
        billed = not cause.get("preempted")
        if billed and rec["attempt"] > 0 \
                and rec["billed"] >= rec["max_attempts"]:
            self._fail(rec, f"retry budget exhausted "
                            f"({rec['billed']}/{rec['max_attempts']} "
                            f"attempts): {cause.get('detail')}")
            return {"action": "fail", "status": FAILED}
        if billed:
            rec["billed"] += 1
        rec["attempt"] += 1
        label = cause.get("cause") or "unknown"
        self.attempt_counts[label] = self.attempt_counts.get(label, 0) + 1
        if rec["started_ts"] is None:
            rec["started_ts"] = time.time()
        # Backoff: capped-exponential over BILLED retries; preemption
        # relaunches immediately (the departure was planned, the work is
        # idle — waiting buys nothing).
        if rec["attempt"] == 1 or not billed:
            backoff = 0.0
        else:
            base = float(flags.get("RTPU_JOB_BACKOFF_BASE_S"))
            cap = float(flags.get("RTPU_JOB_BACKOFF_MAX_S"))
            backoff = min(base * (2.0 ** max(0, rec["billed"] - 2)), cap)
        # Placement + durable log reference for this attempt: the
        # supervisor's worker log file is where the entrypoint's output
        # lands (actor-attributed), and the reference outlives the
        # worker. The supervisor's run thread races actor_ready — its
        # first attempt_start can arrive before the controller learned
        # which worker hosts it — so wait briefly for the link.
        aid = rec.get("supervisor_actor_id") or ""
        actor = self.ctrl.actors.get(aid)
        for _ in range(100):
            if actor is not None and actor.worker_id:
                break
            await asyncio.sleep(0.05)
            actor = self.ctrl.actors.get(aid)
            rec = self.jobs.get(job_id)
            if rec is None:
                return {"action": "fail", "error": "job evicted"}
        exec_info = {"node_id": actor.node_id if actor else None,
                     "worker_id": actor.worker_id if actor else None,
                     "pgid": None, "pid": None,
                     "attempt": rec["attempt"]}
        rec["exec"] = exec_info
        ref = self.ctrl.worker_log_names.get(exec_info["worker_id"] or "")
        logref = {"attempt": rec["attempt"],
                  "node_id": (ref or {}).get("node_id")
                  or exec_info["node_id"],
                  "worker_id": exec_info["worker_id"],
                  "name": (ref or {}).get("name")}
        logs = rec["attempt_logs"]
        if logref["name"] and not (
                logs and logs[-1].get("name") == logref["name"]
                and logs[-1].get("node_id") == logref["node_id"]):
            logs.append(logref)
        self._set_status(rec, RUNNING, cause=cause.get("cause"))
        if rec["attempt"] == 1:
            self._emit("INFO", "JOB_STARTED",
                       f"job {job_id} started "
                       f"(attempt 1/{rec['max_attempts']})", rec,
                       data={"attempt": 1})
        else:
            self._emit(
                "WARNING", "JOB_RETRYING",
                f"job {job_id} retrying: attempt {rec['attempt']} "
                f"({'free — preempted' if not billed else 'billed '+str(rec['billed'])+'/'+str(rec['max_attempts'])}, "
                f"cause: {cause.get('cause')})", rec,
                data={"attempt": rec["attempt"],
                      "billed": rec["billed"],
                      "cause": cause.get("cause"),
                      "detail": cause.get("detail"),
                      "preempted": not billed,
                      "backoff_s": backoff})
        return {"action": "run", "attempt": rec["attempt"],
                "backoff_s": backoff,
                "max_attempts": rec["max_attempts"]}

    def attempt_exec(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """The supervisor reports the spawned entrypoint's pid/pgid —
        the child-pid state that makes stop/orphan-cleanup work after
        the supervisor itself dies (persisted with the record)."""
        rec = self.jobs.get(msg.get("job_id") or "")
        if rec is None:
            return {"ok": False}
        if int(msg.get("attempt") or 0) != rec["attempt"]:
            return {"ok": False, "stale": True}
        ex = rec.get("exec") or {}
        ex["pid"] = msg.get("pid")
        ex["pgid"] = msg.get("pgid")
        rec["exec"] = ex
        self.ctrl._state_dirty = True
        if rec.get("stop_requested"):
            # stop_job raced the spawn: the supervisor's stop path kills
            # the group too, but don't rely on it having seen the event.
            self._spawn_exec_kill(dict(ex))
        return {"ok": True}

    def attempt_done(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """The entrypoint exited. Decide: succeed / stop / retry / fail."""
        rec = self.jobs.get(msg.get("job_id") or "")
        if rec is None:
            return {"action": "exit"}
        if int(msg.get("attempt") or 0) != rec["attempt"]:
            return {"action": "exit", "stale": True}
        if rec["status"] in TERMINAL_STATES:
            return {"action": "exit", "status": rec["status"]}
        rc = msg.get("returncode")
        tail = (msg.get("tail") or "")[-4096:]
        ex = rec.get("exec")
        if ex:
            ex["pgid"] = None
            ex["pid"] = None
        rec["returncode"] = rc
        if rec.get("stop_requested"):
            self._set_status(rec, STOPPED, cause="stopped")
            self._emit("INFO", "JOB_STOPPED",
                       f"job {rec['job_id']} stopped "
                       f"(returncode {rc})", rec,
                       data={"returncode": rc})
            return {"action": "exit", "status": STOPPED}
        if rc == 0:
            self._set_status(rec, SUCCEEDED, cause="exit 0")
            self._emit("INFO", "JOB_SUCCEEDED",
                       f"job {rec['job_id']} succeeded after "
                       f"{rec['attempt']} attempt(s)", rec,
                       data={"attempts": rec["attempt"],
                             "billed": rec["billed"]})
            return {"action": "exit", "status": SUCCEEDED}
        rec["last_tail"] = tail
        rec["message"] = f"attempt {rec['attempt']} exited {rc}"
        if rec["billed"] < rec["max_attempts"]:
            rec["pending_cause"] = {"cause": "exit",
                                    "detail": f"exit code {rc}",
                                    "preempted": False}
            self._set_status(rec, RETRYING, cause=f"exit:{rc}")
            self._emit("WARNING", "JOB_ATTEMPT_FAILED",
                       f"job {rec['job_id']} attempt {rec['attempt']} "
                       f"exited {rc} "
                       f"({rec['billed']}/{rec['max_attempts']} billed)",
                       rec,
                       data={"attempt": rec["attempt"],
                             "returncode": rc, "tail": tail[-1024:]})
            return {"action": "retry"}
        self._fail(rec, f"attempt {rec['attempt']} exited {rc}; "
                        f"budget exhausted "
                        f"({rec['billed']}/{rec['max_attempts']})")
        return {"action": "exit", "status": FAILED}

    def _fail(self, rec: Dict[str, Any], message: str) -> None:
        rec["message"] = message
        self._set_status(rec, FAILED, cause=message)
        self._emit("ERROR", "JOB_FAILED",
                   f"job {rec['job_id']} failed: {message}", rec,
                   data={"attempts": rec["attempt"],
                         "billed": rec["billed"],
                         "returncode": rec.get("returncode"),
                         "tail": rec.get("last_tail") or ""})

    # -------------------------------------------- supervisor-death hooks
    # Called from the controller's actor lifecycle paths, keyed on the
    # `_job:` actor-name prefix.

    def note_supervisor_died(self, actor, err: Exception,
                             preempted: bool, fatal: bool) -> None:
        job_id = (actor.name or "")[len(SUPERVISOR_PREFIX):]
        rec = self.jobs.get(job_id)
        if rec is None or rec["status"] in TERMINAL_STATES:
            return
        ex = rec.get("exec")
        if ex and ex.get("pgid"):
            # The entrypoint's process group outlived its supervisor:
            # tear it down so the replacement attempt never overlaps it.
            self._spawn_exec_kill(dict(ex))
            ex["pgid"] = None
        if fatal:
            self._fail(rec, f"supervisor died permanently: "
                            f"{type(err).__name__}: {err}")
            return
        rec["pending_cause"] = {
            "cause": "preempted" if preempted else "worker_died",
            "detail": f"{type(err).__name__}: {err}",
            "preempted": preempted}
        self._emit("WARNING", "JOB_SUPERVISOR_DIED",
                   f"job {job_id} supervisor died "
                   f"({'preempted' if preempted else 'crash'}): {err} — "
                   f"rescheduling", rec,
                   node_id=actor.node_id,
                   data={"cause": f"{type(err).__name__}: {err}",
                         "preempted": preempted})
        self._touch(rec)

    def note_supervisor_migrating(self, actor, node) -> None:
        """Live drain-migration: the supervisor instance moves with its
        state, but its entrypoint subprocess cannot — the restored
        supervisor relaunches, and the relaunch is a planned departure
        (zero budget)."""
        job_id = (actor.name or "")[len(SUPERVISOR_PREFIX):]
        rec = self.jobs.get(job_id)
        if rec is None or rec["status"] in TERMINAL_STATES:
            return
        rec["pending_cause"] = {
            "cause": "preempted",
            "detail": f"node {node.node_id[:8]} draining "
                      f"({node.drain_reason or 'drain'})",
            "preempted": True}
        self.ctrl._state_dirty = True

    def _spawn_exec_kill(self, ex: Dict[str, Any]) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        loop.create_task(self._kill_exec(ex))

    async def _kill_exec(self, ex: Dict[str, Any]) -> None:
        """Kill one attempt's process group wherever it lives: via the
        owning host agent's kill_pgid handler, or locally for head-host
        and virtual-node spawns."""
        pgid = ex.get("pgid")
        if not pgid:
            return
        grace = float(flags.get("RTPU_JOB_STOP_GRACE_S"))
        node = self.ctrl.nodes.get(ex.get("node_id") or "")
        try:
            if node is not None and node.agent_conn is not None:
                await node.agent_conn.request(
                    {"kind": "kill_pgid", "pgid": pgid, "grace_s": grace},
                    timeout=grace + 10)
            elif node is not None and (
                    not node.host_id
                    or node.host_id == self.ctrl.host_id):
                # Head-host / virtual-node spawn (or the node's agent died
                # but the processes share this machine): kill locally. A
                # pgid from a genuinely different host must NOT be
                # signalled here — the number could collide with an
                # unrelated local group.
                await asyncio.to_thread(kill_process_group, pgid, grace)
        except Exception:
            pass

    # ------------------------------------------------------------- queries

    def status(self, job_id: str) -> Dict[str, Any]:
        rec = self.jobs.get(job_id)
        if rec is None:
            return {"error": f"unknown job {job_id!r}"}
        return {"record": self.public(rec), "seq": rec["seq"]}

    def list(self) -> List[Dict[str, Any]]:
        return [self.public(r) for r in self.jobs.values()]

    async def wait(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Long-poll on one job's status sequence (the get_events
        after_seq pattern): returns as soon as the record changed past
        ``after_seq``, immediately for terminal jobs, or when the wait
        window closes."""
        job_id = msg.get("job_id") or ""
        rec = self.jobs.get(job_id)
        if rec is None:
            return {"error": f"unknown job {job_id!r}"}
        after = int(msg.get("after_seq") or 0)
        deadline = time.monotonic() + max(
            0.0, min(float(msg.get("wait_s") or 0), 30.0))
        while (rec["seq"] <= after
               and rec["status"] not in TERMINAL_STATES):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            await self._wait_change(job_id, remaining)
            rec = self.jobs.get(job_id)
            if rec is None:
                return {"error": f"unknown job {job_id!r}"}
        return {"record": self.public(rec), "seq": rec["seq"]}

    async def stop(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Stop a job: mark it (persisted), nudge the supervisor over
        pubsub (it escalates through the entrypoint's process group),
        and kill the recorded process group directly in case the
        supervisor is mid-failover."""
        job_id = msg.get("job_id") or ""
        rec = self.jobs.get(job_id)
        if rec is None:
            return {"ok": False, "error": f"unknown job {job_id!r}"}
        if rec["status"] in TERMINAL_STATES:
            return {"ok": True, "status": rec["status"]}
        rec["stop_requested"] = True
        self._touch(rec)
        try:
            await self.ctrl._h_publish(
                None, {"channel": stop_channel(job_id),
                       "data": {"op": "stop"}})
        except Exception:
            pass
        ex = rec.get("exec")
        if ex and ex.get("pgid"):
            self._spawn_exec_kill(dict(ex))
        aid = rec.get("supervisor_actor_id") or ""
        actor = self.ctrl.actors.get(aid)
        if actor is None or actor.state == "dead":
            self._set_status(rec, STOPPED, cause="stop requested")
            self._emit("INFO", "JOB_STOPPED",
                       f"job {job_id} stopped (no live supervisor)", rec)
        return {"ok": True, "status": rec["status"]}

    def stop_ack(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Supervisor acknowledges a stop that arrived while no attempt
        was running (e.g. during backoff)."""
        rec = self.jobs.get(msg.get("job_id") or "")
        if rec is None or rec["status"] in TERMINAL_STATES:
            return {"ok": True}
        if rec.get("stop_requested"):
            self._set_status(rec, STOPPED, cause="stop requested")
            self._emit("INFO", "JOB_STOPPED",
                       f"job {rec['job_id']} stopped", rec)
        return {"ok": True}

    async def logs(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Durable job logs: walk the per-attempt log-file references in
        order, reading each file's supervisor-attributed ranges through
        the cluster log plane. The cursor is {i: attempt-ref index,
        offset: attributed-stream offset}, so a follow stream crosses a
        supervisor failover by rolling from the dead attempt's file
        (wherever it lives) onto the replacement's."""
        job_id = msg.get("job_id") or ""
        rec = self.jobs.get(job_id)
        cur = dict(msg.get("cursor") or {})
        cur = {"i": int(cur.get("i") or 0),
               "offset": int(cur.get("offset") or 0)}
        if rec is None:
            return {"error": f"unknown job {job_id!r}", "data": "",
                    "cursor": cur, "eof": True, "status": None}
        max_bytes = min(int(msg.get("max_bytes") or 65536), 1 << 20)
        deadline = time.monotonic() + max(
            0.0, min(float(msg.get("wait_s") or 0), 10.0))
        while True:
            refs = [r for r in rec["attempt_logs"] if r.get("name")]
            terminal = rec["status"] in TERMINAL_STATES
            if cur["i"] >= len(refs):
                if terminal:
                    return {"data": "", "cursor": cur, "eof": True,
                            "status": rec["status"]}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"data": "", "cursor": cur, "eof": False,
                            "status": rec["status"]}
                await self._wait_change(job_id, remaining)
                rec = self.jobs.get(job_id) or rec
                continue
            ref = refs[cur["i"]]
            last = cur["i"] == len(refs) - 1
            m: Dict[str, Any] = {
                "name": ref["name"], "node_id": ref.get("node_id"),
                "actor_id": rec.get("supervisor_actor_id"),
                "offset": cur["offset"], "max_bytes": max_bytes}
            if last and not terminal:
                m["wait_s"] = max(
                    0.0, min(deadline - time.monotonic(), 10.0))
            out = await self.ctrl._fetch_log(m)
            data = out.get("data") or ""
            if data:
                cur = {"i": cur["i"],
                       "offset": int(out.get("offset")
                                     or cur["offset"] + len(data))}
                return {"data": data, "cursor": cur, "eof": False,
                        "status": rec["status"]}
            if not last:
                # This attempt's stream is drained (or its host is
                # gone): roll onto the next attempt's file.
                cur = {"i": cur["i"] + 1, "offset": 0}
                continue
            if terminal:
                return {"data": "", "cursor": cur, "eof": True,
                        "status": rec["status"]}
            if time.monotonic() >= deadline:
                return {"data": "", "cursor": cur, "eof": False,
                        "status": rec["status"]}

    # -------------------------------------------------------- persistence

    def snapshot(self) -> Dict[str, Any]:
        return {"jobs": [dict(r) for r in self.jobs.values()],
                "attempt_counts": dict(self.attempt_counts),
                "runtime_hist": {
                    "buckets": list(self.runtime_hist["buckets"]),
                    "sum": self.runtime_hist["sum"],
                    "count": self.runtime_hist["count"]}}

    def restore(self, snap: Optional[Dict[str, Any]]) -> None:
        if not snap:
            return
        for rec in snap.get("jobs") or []:
            if not isinstance(rec, dict) or not rec.get("job_id"):
                continue
            self.jobs[rec["job_id"]] = rec
        self.attempt_counts.update(snap.get("attempt_counts") or {})
        rh = snap.get("runtime_hist") or {}
        if rh.get("buckets") and len(rh["buckets"]) == len(
                JOB_RUNTIME_BOUNDARIES):
            self.runtime_hist = {"buckets": list(rh["buckets"]),
                                 "sum": float(rh.get("sum", 0.0)),
                                 "count": int(rh.get("count", 0))}
        self._gc_legacy_kv()

    # ------------------------------------------------------------- metrics

    def status_counts(self) -> Dict[Tuple, int]:
        out: Dict[Tuple, int] = {}
        for rec in self.jobs.values():
            key = (("status", rec["status"]),)
            out[key] = out.get(key, 0) + 1
        return out

    def attempt_count_data(self) -> Dict[Tuple, int]:
        return {(("cause", c),): n
                for c, n in self.attempt_counts.items()}

    def runtime_hist_data(self) -> Dict[Tuple, Any]:
        h = self.runtime_hist
        if not h["count"]:
            return {}
        return {(): {"buckets": list(h["buckets"]),
                     "sum": round(h["sum"], 3), "count": h["count"]}}
