"""Cluster controller: control plane for the distributed futures core.

Role-equivalent to the reference's GCS server + cluster scheduler
(ray: src/ray/gcs/gcs_server/gcs_server.h:78, gcs_actor_manager.h:281,
gcs_placement_group_manager.h:230, raylet/scheduling/cluster_task_manager.h:70),
collapsed into one asyncio service for the single-host/virtual-multi-node
topology that round 1 targets. Responsibilities:

- membership: virtual nodes + worker processes (the reference's raylet worker
  pool, worker_pool.h:159, becomes a per-node on-demand process pool here),
- the object directory / memory store for inlined objects,
- task scheduling with resource accounting, dependency resolution, and
  scheduling strategies (DEFAULT/SPREAD/node-affinity/placement-group; the
  reference's policy suite is raylet/scheduling/policy/),
- the actor directory with named/detached actors and ordered per-actor
  dispatch (gcs_actor_manager.h semantics),
- placement groups with PACK/SPREAD/STRICT_PACK/STRICT_SPREAD bundle
  reservation (bundle_scheduling_policy.h:82-106),
- an internal KV store (gcs_kv_manager) and a tiny pubsub.

TPU-first note: the controller is deliberately *off* the training hot path.
Mesh formation (ray_tpu.parallel) uses it only to place host processes and
exchange coordinator addresses; every per-step byte moves inside XLA programs.
"""
from __future__ import annotations

from ray_tpu import flags

import asyncio
import collections
import json
import os
import subprocess
import sys
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from . import protocol
from .ids import ActorID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID
from .object_store import ObjectLocation, free_location

# Worker processes a node may grow to (the reference caps via resources; this
# is a backstop against runaway spawning on the 1-CPU CI host).
MAX_WORKERS_PER_NODE = flags.get("RTPU_MAX_WORKERS_PER_NODE")

# Flight-recorder phase -> derived Prometheus histogram (reference: the
# GcsTaskManager-fed task latency breakdowns behind `ray summary`). Served
# from app_metrics so the exposition/grafana paths pick them up unchanged.
PHASE_METRIC_NAMES = {
    "scheduling_delay_s": "rtpu_task_scheduling_delay_s",
    "queue_wait_s": "rtpu_task_queue_wait_s",
    "arg_fetch_s": "rtpu_task_arg_fetch_s",
    "exec_s": "rtpu_task_exec_s",
    "result_store_s": "rtpu_task_result_store_s",
}
PHASE_METRIC_HELP = {
    "rtpu_task_scheduling_delay_s": "Task submit -> dispatch arrival at a worker",
    "rtpu_task_queue_wait_s": "Worker-local queue wait before execution",
    "rtpu_task_arg_fetch_s": "Argument location lookup + fetch + deserialize",
    "rtpu_task_exec_s": "User-code execution",
    "rtpu_task_result_store_s": "Result serialize + object-store put",
}
PHASE_BOUNDARIES = [0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05,
                    0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0]

# Every core metric family the controller exports: name -> (type, help).
# Single source of truth for the /metrics exposition, the telemetry ring
# (core/telemetry.py samples _metrics_families each step), grafana panel
# derivation, and the metrics lint (tests/test_metrics_lint.py) that
# refuses rtpu_* names without help text.
CORE_METRIC_META: Dict[str, Tuple[str, str]] = {
    "rtpu_tasks": ("gauge", "Tasks currently in each lifecycle state "
                            "(bounded event window)"),
    "rtpu_pending_tasks": ("gauge", "Tasks waiting in the scheduler queue"),
    "rtpu_workers": ("gauge", "Registered worker processes"),
    "rtpu_actors": ("gauge", "Registered actors"),
    "rtpu_nodes_alive": ("gauge", "Nodes currently alive"),
    "rtpu_objects": ("gauge", "Objects tracked by the object directory"),
    "rtpu_nodes": ("gauge", "Nodes by drain-lifecycle state "
                            "(alive/draining/drained/dead)"),
    "rtpu_node_drains_total": ("counter", "Node drains initiated, "
                                          "by reason"),
    "rtpu_uptime_seconds": ("counter", "Controller uptime"),
    "rtpu_objects_spilled_total": ("counter", "Objects spilled to disk"),
    "rtpu_broadcast_bytes_total": (
        "counter", "Object bytes moved by broadcast chains, by role "
                   "(source/hop)"),
    "rtpu_object_replicas": ("gauge", "Extra object replicas held by "
                                      "broadcast chain hops"),
    "rtpu_actor_checkpoints_total": (
        "counter", "Durable actor checkpoints stored by the controller"),
    "rtpu_actor_checkpoint_bytes": (
        "counter", "Cumulative bytes of stored actor checkpoint records"),
    "rtpu_leases_active": ("gauge", "Active direct-dispatch worker "
                                    "leases"),
    "rtpu_lease_events_total": (
        "counter", "Direct-dispatch lease lifecycle: blocks/leases "
                   "granted, reclaim nudges sent, grants refused under "
                   "memory pressure"),
    "rtpu_arena_used_bytes": ("gauge", "Controller-host object arena "
                                       "bytes in use"),
    "rtpu_arena_capacity_bytes": ("gauge", "Controller-host object arena "
                                           "capacity"),
    "rtpu_node_arena_used_bytes": ("gauge", "Per-node object arena bytes "
                                            "in use (agent heartbeats)"),
    "rtpu_node_mem_fraction": (
        "gauge", "Per-node host memory utilization 0-1 (agent "
                 "heartbeats; controller-host sample for local nodes)"),
    "rtpu_node_cpu_percent": (
        "gauge", "Per-node host CPU percent (agent heartbeats; "
                 "controller-host sample for local nodes)"),
    "rtpu_worker_log_bytes": ("gauge", "Bytes of worker log files per "
                                       "node"),
    "rtpu_events_total": ("counter", "Cluster events recorded, by source "
                                     "and severity"),
    "rtpu_worker_cpu_percent": ("gauge", "Worker process CPU percent "
                                         "(host-agent heartbeats)"),
    "rtpu_worker_rss_bytes": ("gauge", "Worker process resident set size "
                                       "(host-agent heartbeats)"),
    "rtpu_rpc_handled_total": ("counter", "Control-plane RPCs handled, "
                                          "by message kind"),
    "rtpu_rpc_handler_seconds_total": (
        "counter", "Cumulative RPC handler seconds, by message kind"),
    "rtpu_object_store_bytes": (
        "gauge", "Object-store bytes tracked by the directory, by node "
                 "and storage tier (inline/shm/arena/spill/replica) — "
                 "the census gauge behind `rtpu memory`"),
    "rtpu_object_store_fill_fraction": (
        "gauge", "Per-node object arena fill fraction 0-1 (used/capacity "
                 "from agent heartbeats) — drives the "
                 "object_store_mem_high alert rule"),
    "rtpu_node_spill_bytes": (
        "gauge", "Per-node bytes of spilled objects on disk (host-wide "
                 "spill-dir scan riding agent heartbeats)"),
    "rtpu_object_leaks_total": (
        "counter", "Objects flagged OBJECT_LEAK_SUSPECT by the leak "
                   "watchdog (old refs whose owner is dead/unreachable)"),
    "rtpu_jobs": ("gauge", "Jobs in the controller job table, by status "
                           "(PENDING/RUNNING/RETRYING/SUCCEEDED/FAILED/"
                           "STOPPED)"),
    "rtpu_job_attempts_total": (
        "counter", "Entrypoint launches across all jobs, by cause "
                   "(initial/exit/worker_died/preempted/"
                   "supervisor_restart) — the rate behind the "
                   "job_flapping alert"),
    "rtpu_job_runtime_s": (
        "histogram", "End-to-end runtime of terminal jobs, "
                     "submitted-to-finished (seconds)"),
}

# Families whose HELP/TYPE lines are emitted even with no samples yet
# (the exposition always carried these headers; conditional families —
# drains, arena, per-node/per-pid gauges — appear once they have data).
_ALWAYS_EXPORT = frozenset({
    "rtpu_tasks", "rtpu_pending_tasks", "rtpu_workers", "rtpu_actors",
    "rtpu_nodes_alive", "rtpu_objects", "rtpu_nodes",
    "rtpu_uptime_seconds", "rtpu_objects_spilled_total",
    "rtpu_broadcast_bytes_total", "rtpu_object_replicas",
    "rtpu_actor_checkpoints_total", "rtpu_actor_checkpoint_bytes",
    "rtpu_leases_active", "rtpu_lease_events_total",
})


def _hist_quantile(bounds: List[float], h: Dict[str, Any], q: float) -> float:
    """Percentile estimate from cumulative bucket counts (the
    histogram_quantile linear interpolation, server-side)."""
    total = h.get("count", 0)
    if not total:
        return 0.0
    target = q * total
    cum = 0.0
    lo = 0.0
    for i, b in enumerate(bounds):
        c = h["buckets"][i]
        if c and cum + c >= target:
            return lo + (b - lo) * ((target - cum) / c)
        cum += c
        lo = b
    return bounds[-1] if bounds else 0.0  # +Inf bucket clamps to last edge


def _res_fits(avail: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in need.items())


def _res_sub(avail: Dict[str, float], need: Dict[str, float]) -> None:
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) - v


def _res_add(avail: Dict[str, float], need: Dict[str, float]) -> None:
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) + v


@dataclass
class NodeInfo:
    node_id: str
    resources: Dict[str, float]
    available: Dict[str, float]
    index: int
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    spawning: int = 0
    spawning_tpu: int = 0
    # env_hash -> in-flight spawn count: one pending env spawn satisfies all
    # queued wakeups for that env (same rationale as spawning_tpu).
    spawning_envs: Dict[str, int] = field(default_factory=dict)
    workers: Set[str] = field(default_factory=set)
    # Host-agent fields (None for in-controller virtual nodes): the agent's
    # control connection, its pull-server address, and its host identity
    # (reference: raylet registration with the GCS, gcs_node_manager.h).
    agent_conn: Optional[protocol.Connection] = None
    agent_addr: Optional[Tuple[str, int]] = None
    host_id: Optional[str] = None
    last_heartbeat: float = 0.0
    arena_stats: Dict[str, int] = field(default_factory=dict)
    # Host memory usage fraction (agent heartbeats / controller psutil for
    # local nodes); drives the memory monitor's kill decisions.
    mem_fraction: float = 0.0
    # Host CPU utilization percent (agent heartbeats; local nodes sample
    # at cluster_state time) — the `rtpu status` CPU% column.
    cpu_percent: float = 0.0
    # Unallocated TPU chip ids on locally-spawned (agent-less) nodes: the
    # unit-instance side of the "TPU" float resource (reference: per-instance
    # GPU accounting, resource_instance_set.h). Agent-managed nodes track
    # this on the agent, which owns the worker processes.
    tpu_free: List[int] = field(default_factory=list)
    # Per-worker-process cpu%/rss from the agent heartbeat (dashboard
    # reporter parity); pid -> {cpu_percent, rss}.
    proc_stats: Dict[str, Dict[str, float]] = field(default_factory=dict)
    # Total bytes of worker log files on the host (agent heartbeats;
    # exported as the rtpu_worker_log_bytes gauge).
    log_bytes: int = 0
    # Drain state machine (reference: autoscaler.proto:334 DrainNode +
    # node_manager.proto:391 DrainRaylet): alive -> draining -> drained.
    # A draining node takes no new placements; at the deadline its running
    # work re-queues with the preempted flag and the node leaves.
    draining: bool = False
    drained: bool = False
    drain_reason: str = ""
    drain_deadline: float = 0.0  # wall clock (survives a controller bounce)
    # Two-phase failure detector (SWIM-style suspect phase in front of the
    # death declaration): heartbeat silence past RTPU_NODE_TIMEOUT_S marks
    # the node suspect — scheduling pauses, actor calls buffer, nothing is
    # killed — and only silence past RTPU_DEAD_TIMEOUT_S declares death, so
    # a healed partition rejoins without actor churn or double-allocation.
    suspect: bool = False
    suspect_since: float = 0.0  # monotonic
    # Host-wide spill usage {files, bytes} (agent heartbeats; local nodes
    # sample at metrics/census time) — census "spill" tier + `rtpu status`.
    spill_stats: Dict[str, int] = field(default_factory=dict)
    # Channel-fabric footprint {segments, bytes}: live rtpu_ch_* shm rings
    # on the host (agent heartbeats; local nodes scan at cluster_state
    # time) — the node-level view of the compiled-DAG channel plane.
    channel_stats: Dict[str, int] = field(default_factory=dict)


@dataclass
class WorkerInfo:
    worker_id: str
    node_id: str
    conn: protocol.Connection
    state: str = "idle"  # idle | task | actor
    current_task: Optional[str] = None
    actor_ids: Set[str] = field(default_factory=set)
    proc: Optional[subprocess.Popen] = None
    spawn_token: Optional[str] = None  # set for agent-spawned workers
    # Runtime-env identity: a worker only runs tasks with the same env hash
    # (reference: worker_pool.h runtime_env_hash pool keying).
    env_hash: str = ""
    pid: int = 0  # worker OS pid (joins agent heartbeat proc_stats)
    # TPU-capable workers carry the accelerator runtime (axon/PJRT plugin)
    # and cost seconds to start; plain workers skip it and start in ~0.3s.
    tpu_capable: bool = False
    # Chip ids assigned at spawn (TPU_VISIBLE_CHIPS); returned to the
    # node's tpu_free pool when the worker dies. Local-spawn nodes only.
    chip_ids: List[int] = field(default_factory=list)
    # Port of the worker's direct-dispatch server (0 = none); peers push
    # actor tasks there without a controller hop.
    direct_port: int = 0
    # When the current task was dispatched (memory-monitor victim order)
    # and whether the monitor chose this worker (OOM error attribution).
    task_started: float = 0.0
    oom_killed: bool = False


@dataclass
class ActorInfo:
    actor_id: str
    name: Optional[str]
    state: str = "pending"  # pending | alive | restarting | dead
    worker_id: Optional[str] = None
    node_id: Optional[str] = None
    resources: Dict[str, float] = field(default_factory=dict)
    pg: Optional[Tuple[str, int]] = None  # (pg_id, bundle_index)
    creation_error: Optional[Exception] = None
    pending_calls: List[Dict[str, Any]] = field(default_factory=list)
    detached: bool = False
    reserved: bool = False
    creation_task_id: Optional[str] = None
    order_lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    # Fault tolerance (reference: gcs_actor_manager.h:88 restart-on-failure):
    # the creation spec is kept so the actor can be rebuilt elsewhere.
    max_restarts: int = 0
    restart_count: int = 0
    creation_spec: Optional[Dict[str, Any]] = None
    # Newest durable checkpoint shipped by the hosting worker:
    # {epoch, blob, bytes, ts}. A crash restart restores it instead of
    # re-running the constructor (core/checkpoint.py record format).
    checkpoint: Optional[Dict[str, Any]] = None


@dataclass
class GeneratorState:
    """Server-side state of one streaming task (reference: streaming
    generator returns, core_worker.proto ReportGeneratorItemReturns +
    _raylet.pyx:273). Items are ordinary objects; this tracks their order,
    completion, and the consumer-driven backpressure window."""

    task_id: str
    window: int = 16
    items: List[str] = field(default_factory=list)
    consumed: int = 0
    done: bool = False
    closed: bool = False  # consumer dropped the generator
    error: Optional[Exception] = None
    wake: asyncio.Event = field(default_factory=asyncio.Event)  # consumers
    drain: asyncio.Event = field(default_factory=asyncio.Event)  # producer


@dataclass
class Bundle:
    resources: Dict[str, float]
    node_id: Optional[str] = None
    available: Dict[str, float] = field(default_factory=dict)


@dataclass
class PGInfo:
    pg_id: str
    bundles: List[Bundle]
    strategy: str
    name: Optional[str]
    state: str = "pending"  # pending | ready | removed
    ready_event: asyncio.Event = field(default_factory=asyncio.Event)


class _PendingQueue:
    """Scheduling queue grouped by placement signature.

    All tasks with the same (resources, strategy, pg, env) signature are
    interchangeable to the scheduler; one failed placement attempt rules
    out the whole group for that pass. Grouping makes a pass
    O(#groups + #placements) instead of O(#pending) — a 10k-task
    homogeneous wave costs one signature lookup per pass, not 10k
    re-examinations (reference: lease-by-shape batching in
    cluster_task_manager/direct_task_transport: one lease request per
    TaskSpec shape, not per task).
    """

    def __init__(self) -> None:
        self.groups: "collections.OrderedDict[tuple, collections.deque]" = (
            collections.OrderedDict())
        self._count = 0

    @staticmethod
    def sig_of(spec: Dict[str, Any]) -> tuple:
        return (
            tuple(sorted(spec.get("resources", {}).items())),
            repr(spec.get("scheduling")),
            spec.get("pg"),
            spec.get("env_hash") or "",
        )

    def append(self, spec: Dict[str, Any]) -> None:
        self.groups.setdefault(self.sig_of(spec),
                               collections.deque()).append(
            spec["task_id"])
        self._count += 1

    def remove(self, task_id: str) -> None:
        for sig, q in list(self.groups.items()):
            if task_id in q:
                q.remove(task_id)
                self._count -= 1
                if not q:
                    del self.groups[sig]
                return

    def discard_missing(self, task_id: str, sig: tuple) -> None:
        """Drop a task popped during scheduling whose spec is gone."""
        self._count -= 1

    def ids(self) -> List[str]:
        return [tid for q in self.groups.values() for tid in q]

    def __len__(self) -> int:
        return self._count



class Controller:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self.server: Optional[asyncio.base_events.Server] = None
        self.nodes: Dict[str, NodeInfo] = {}
        self.workers: Dict[str, WorkerInfo] = {}
        self.actors: Dict[str, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], str] = {}  # (namespace, name) -> actor_id
        # Compiled DAGs with live channel plans (dag_id -> registration):
        # bookkeeping only — the channel data plane never touches the
        # controller between compile and teardown.
        self.compiled_dags: Dict[str, Dict[str, Any]] = {}
        self.objects: Dict[str, ObjectLocation] = {}
        # Broadcast replicas: oid -> {node_id: ObjectLocation} — full extra
        # copies of an object's bytes on other hosts (reference: the object
        # directory tracking multiple locations per object,
        # object_directory.h). get_locations prefers the consumer-local
        # copy; remote consumers get the list for parallel pulls.
        self.object_replicas: Dict[str, Dict[str, ObjectLocation]] = {}
        # In-flight broadcast rounds: bid -> shared completion state.
        self._broadcasts: Dict[str, Dict[str, Any]] = {}
        # Cumulative broadcast byte accounting for /metrics
        # (rtpu_broadcast_bytes_total{role}).
        self.broadcast_bytes: Dict[str, int] = {"source": 0, "hop": 0}
        self.object_waiters: Dict[str, List[asyncio.Event]] = {}
        # oid -> callbacks fired (once) when the object's location lands;
        # the incremental path used by wait (vs the Event-based get path).
        self.object_callbacks: Dict[str, List[Any]] = {}
        # Last-touched times drive cold-object selection for arena spilling.
        self.object_touch: Dict[str, float] = {}
        # Census + leak-watchdog bookkeeping: first-registration wall time
        # per directory object, the registering connection for driver/worker
        # put paths (a closed conn whose old objects linger = leak suspect),
        # the once-per-object dedup set, and the cumulative
        # rtpu_object_leaks_total counter.
        self.object_created: Dict[str, float] = {}
        self.object_src: Dict[str, Any] = {}
        self._leak_reported: Set[str] = set()
        self.leak_count = 0
        self._leak_task: Optional[asyncio.Task] = None
        self.spilled_count = 0
        self.rpc_counts: Dict[str, int] = {}  # message kind -> count
        # (due_time, arena_oid) for spilled arena copies awaiting deletion.
        self._deferred_arena_deletes: List[Tuple[float, int]] = []
        self.tasks: Dict[str, Dict[str, Any]] = {}  # pending/running task specs
        self.pending_queue = _PendingQueue()  # tasks awaiting scheduling
        self.generators: Dict[str, GeneratorState] = {}  # streaming tasks
        # Bounded lineage: completed task specs keyed by their return object
        # ids, so a lost object's producing task can re-execute (reference:
        # object_recovery_manager.h + lineage in reference_count.h).
        import collections as _collections

        self.lineage: "_collections.OrderedDict[str, Dict[str, Any]]" = (
            _collections.OrderedDict())
        self.lineage_max = flags.get("RTPU_LINEAGE_MAX")
        # Ownership tree for recursive cancel: parent task id -> live child
        # task ids, plus child -> parent back-pointers for pruning. Edges
        # come from spec["parent_task_id"] (controller-path submissions) or
        # fire-and-forget task_lineage notes (direct pushes). A finished
        # task drops its own parent edge but keeps its children set so a
        # recursive cancel can still traverse THROUGH a finished middle
        # task to running grandchildren; the set self-cleans as they finish.
        self.task_children: Dict[str, Set[str]] = {}
        self.task_parent: Dict[str, str] = {}
        # Finished-task return-oid -> task id (bounded FIFO): a recursive
        # cancel of an ALREADY-FINISHED parent must still locate the
        # subtree root to kill its running descendants.
        self.done_oid2task: "_collections.OrderedDict[str, str]" = (
            _collections.OrderedDict())
        self.functions: Dict[str, bytes] = {}  # function/class table (gcs_function_manager)
        self.kv: Dict[Tuple[str, str], bytes] = {}
        self.pgs: Dict[str, PGInfo] = {}
        self.named_pgs: Dict[str, str] = {}
        self.subs: Dict[str, List[protocol.Connection]] = {}  # pubsub channel -> conns
        # Per-connection publish coalescing buffers: id(conn) -> [conn, items]
        self._pubsub_pending: Dict[int, list] = {}
        self.driver_conns: Set[protocol.Connection] = set()
        # Direct-dispatch worker leases (lease_id -> {worker_id, node_id,
        # resources, owner conn}) and on-demand profiling collection state.
        self._leases: Dict[str, Dict[str, Any]] = {}
        # Lease-block accounting (/metrics rtpu_lease_* counters): blocks
        # granted, individual leases granted, reclaim nudges, and grants
        # refused at admission (the direct path's spillback analog).
        self.lease_stats: Dict[str, int] = {
            "blocks": 0, "granted": 0, "reclaims": 0, "mem_refused": 0}
        # Actor-checkpoint accounting (rtpu_actor_checkpoints_total /
        # rtpu_actor_checkpoint_bytes on /metrics).
        self.ckpt_stats: Dict[str, int] = {"count": 0, "bytes": 0}
        self._profiles: Dict[str, Dict[str, Any]] = {}
        self._last_reclaim_nudge = 0.0
        # App-defined metrics (util/metrics.py): name -> {type, help,
        # boundaries, data {tags_tuple: value|histogram-state}}.
        self.app_metrics: Dict[str, dict] = {}
        self._node_counter = 0
        # Drain bookkeeping: per-reason completed-drain counters (the
        # rtpu_node_drains_total{reason} metric) and the in-progress drain
        # table (node_id -> {reason, deadline}) persisted across controller
        # bounces so a drain survives a head restart.
        self.drain_counts: Dict[str, int] = {}
        self.pending_drains: Dict[str, Dict[str, Any]] = {}
        self._drain_tasks: Dict[str, asyncio.Task] = {}
        self._spawned_procs: Dict[str, subprocess.Popen] = {}  # spawn_token -> proc
        self._chip_alloc: Dict[str, List[int]] = {}  # spawn_token -> TPU chip ids
        self._tpu_spawn_tokens: Set[str] = set()  # tokens of TPU-capable spawns
        self._agent_spawns: Dict[str, str] = {}  # outstanding agent spawn token -> node_id
        self._spawn_env_hash: Dict[str, str] = {}  # spawn token -> env hash
        self._sched_wakeup = asyncio.Event()
        self._sched_stuck = False  # last pass left unplaceable queued work
        self._sched_task: Optional[asyncio.Task] = None
        self._health_task: Optional[asyncio.Task] = None
        self._closing = False
        self.start_time = time.time()
        # Bounded task-event history: feeds the state API (`ray list tasks`,
        # summarize) and chrome-trace timeline export (reference:
        # TaskEventBuffer -> GcsTaskManager, task_event_buffer.h:206).
        import collections

        self.task_events: "collections.deque" = collections.deque(
            maxlen=flags.get("RTPU_TASK_EVENTS_MAX"))
        # Cluster-wide finished tracing spans shipped by worker flight
        # recorders (util/tracing.py get_cluster_spans backend).
        self.cluster_spans: "collections.deque" = collections.deque(
            maxlen=flags.get("RTPU_SPANS_MAX"))
        # Serve request ledger (serve/trace.py): request_id -> folded row
        # of hop spans + the terminal record. Bounded by
        # RTPU_SERVE_LEDGER_MAX with slow/shed/deadline rows retained
        # ahead of LRU eviction (slow-request auto-capture).
        self.serve_ledger: "collections.OrderedDict[str, Dict[str, Any]]" = (
            collections.OrderedDict())
        # Cluster log index: worker_id -> {node_id, name} of its log file,
        # kept after the worker dies so `rtpu logs --task-id/--worker-id`
        # can route post-mortem fetches to the owning host (bounded).
        self.worker_log_names: "collections.OrderedDict[str, Dict[str, str]]" = (
            collections.OrderedDict())
        # Node-wide native object arena (plasma-equivalent, src/store).
        # Created here so worker spawns inherit RTPU_ARENA via env; falls
        # back to per-object segments when the native lib is unavailable.
        from . import native_store
        from .object_store import current_host_id

        self._arena = native_store.create_node_arena(uuid.uuid4().hex)
        self.host_id = current_host_id()
        # Durable control-plane state (reference: gcs_storage Redis
        # persistence, ray_config_def.h:402): KV, function table, and
        # detached actors survive controller restarts when a state path is
        # configured (RTPU_STATE_PATH or the CLI's --state-path).
        self.persist_path = flags.get("RTPU_STATE_PATH")
        self._state_dirty = False
        # Durable job table (core/job_manager.py): job records, attempt
        # accounting, and wait_job cursors live here and ride the state
        # snapshot — constructed before _restore_state so a bounce
        # restores the table alongside KV/actors.
        from .job_manager import JobManager

        self.jobs = JobManager(self)
        self._restore_state()
        # Cluster event log (reference: `ray list cluster-events` + the
        # dashboard event feed): bounded ring + JSONL persistence next to
        # the state snapshot, so the feed survives a controller bounce.
        from .events import EventLog

        self.events = EventLog(
            maxlen=flags.get("RTPU_EVENTS_MAX"),
            persist_path=(self.persist_path + ".events.jsonl")
            if self.persist_path else None)
        # Hang-watchdog de-dup: task ids already reported this incarnation
        # (a hung task yields ONE event, not one per sweep).
        self._hang_reported: Set[str] = set()
        self._watchdog_task: Optional[asyncio.Task] = None
        # Telemetry plane (core/telemetry.py): metrics-history ring +
        # alert rules, persisted beside --state-path so `rtpu top`
        # history and firing alerts survive a controller bounce.
        self.tsdb = None
        self.alerts = None
        self._telemetry_task: Optional[asyncio.Task] = None
        if flags.get("RTPU_TSDB"):
            from . import telemetry

            self.tsdb = telemetry.MetricsTSDB(
                step_s=flags.get("RTPU_TSDB_STEP_S"),
                retain=flags.get("RTPU_TSDB_RETAIN"),
                persist_path=(self.persist_path + ".tsdb")
                if self.persist_path else None,
                persist_every_s=flags.get("RTPU_TSDB_PERSIST_S"))
            self.alerts = telemetry.AlertEngine(
                telemetry.load_alert_rules(flags.get("RTPU_ALERT_RULES")),
                self._emit_event)
            self.alerts.restore(self.tsdb.restored_alert_state)

    # ------------------------------------------------------------------ setup

    async def start(self) -> Tuple[str, int]:
        self.server = await asyncio.start_server(self._on_connection, self.host, self.port)
        self.port = self.server.sockets[0].getsockname()[1]
        loop = asyncio.get_running_loop()
        self._sched_task = loop.create_task(self._scheduler_loop())
        self._health_task = loop.create_task(self._health_check_loop())
        if getattr(self, "_restored_detached", None):
            # Restored detached actors re-create right after the adoption
            # grace window, independent of the health loop's cadence.
            async def _resume_after_grace():
                await asyncio.sleep(
                    max(0.0, self._adopt_grace_until - time.monotonic())
                    + 0.05)
                self._resume_detached_actors()

            loop.create_task(_resume_after_grace())
        if flags.get("RTPU_MEMORY_MONITOR"):
            self._memory_task = loop.create_task(self._memory_monitor_loop())
        if flags.get("RTPU_HANG_WATCHDOG") and flags.get("RTPU_EVENTS"):
            # Off => no task, no per-sweep work: the disabled-path perf
            # floor is literally zero controller cycles.
            self._watchdog_task = loop.create_task(self._hang_watchdog_loop())
        if flags.get("RTPU_LEAK_WATCHDOG") and flags.get("RTPU_EVENTS"):
            # Same off-switch contract as the hang watchdog: disabled means
            # no task and zero per-sweep work.
            self._leak_task = loop.create_task(self._leak_watchdog_loop())
        if self.tsdb is not None:
            # RTPU_TSDB=0 => no task, no per-step sampling work: the
            # disabled path is zero controller cycles (perf-floor test).
            self._telemetry_task = loop.create_task(self._telemetry_loop())
        # Resume drains interrupted by a controller bounce: restored
        # (non-agent) nodes become unschedulable immediately, but the
        # drain task itself waits out the reconnect grace — the node's
        # surviving workers haven't re-registered yet, and an instant
        # quiesce check would see an empty node and cut the grace window
        # short mid-task. Agent nodes re-arm on re-register.
        resume: List[str] = []
        for nid in list(self.pending_drains):
            node = self.nodes.get(nid)
            if node is not None:
                st = self.pending_drains[nid]
                node.draining = True
                node.drain_reason = st.get("reason", "manual")
                node.drain_deadline = float(st.get("deadline", 0.0))
                resume.append(nid)
        if resume:
            async def _resume_drains():
                await asyncio.sleep(flags.get("RTPU_RECONNECT_GRACE_S"))
                for nid in resume:
                    if nid in self.pending_drains and nid in self.nodes:
                        self._arm_drain(self.nodes[nid])

            loop.create_task(_resume_drains())
        # Prometheus scrape endpoint (GET /metrics) on an ephemeral port,
        # advertised via cluster_state.metrics_port.
        try:
            self._metrics_server = await asyncio.start_server(
                self._serve_metrics_http, self.host,
                flags.get("RTPU_METRICS_PORT"))
            self.metrics_port = self._metrics_server.sockets[0].getsockname()[1]
        except Exception as e:
            # raw read: flags.get would re-raise on a malformed value, and
            # this handler exists precisely to survive that.
            sys.stderr.write(
                f"[controller] metrics endpoint disabled: {e!r} "
                f"(RTPU_METRICS_PORT={flags.raw('RTPU_METRICS_PORT')})\n")
            self._metrics_server = None
            self.metrics_port = 0
        return self.host, self.port

    def add_node(
        self,
        resources: Dict[str, float],
        labels: Optional[Dict[str, str]] = None,
        node_id: Optional[str] = None,
    ) -> str:
        nid = node_id or NodeID.generate()
        self._node_counter += 1
        self.nodes[nid] = NodeInfo(
            node_id=nid,
            resources=dict(resources),
            available=dict(resources),
            index=self._node_counter,
            labels=labels or {},
            tpu_free=list(range(int(resources.get("TPU", 0)))),
        )
        self._state_dirty = True  # node table persists across restarts
        if getattr(self, "events", None) is not None:
            self._emit_event(
                "INFO", "NODE_ADDED",
                f"node {nid[:8]} joined with {resources}",
                node_id=nid, data={"resources": dict(resources)})
        self._wake_scheduler()
        return nid

    def ensure_head_node(
        self,
        resources: Dict[str, float],
        labels: Optional[Dict[str, str]] = None,
    ) -> str:
        """add_node, unless the state snapshot restored a head node — then
        reuse its identity so workers of the pre-restart controller can
        reconnect under the node id they were spawned with. Capacity is
        refreshed to the caller's view; consumption by adopted workers and
        actors is re-applied as they re-register."""
        for n in self.nodes.values():
            if n.labels.get("head") == "1" and n.agent_conn is None:
                n.resources = dict(resources)
                n.available = dict(resources)
                n.labels.update(labels or {})
                n.alive = True
                # Workers/actors that re-registered before this call keep
                # their grants: re-apply their chip and resource claims to
                # the refreshed capacity instead of clobbering them.
                held = {
                    c for wid in n.workers
                    for c in (self.workers[wid].chip_ids
                              if wid in self.workers else ())
                }
                n.tpu_free = [c for c in
                              range(int(resources.get("TPU", 0)))
                              if c not in held]
                for a in self.actors.values():
                    if a.reserved and a.node_id == n.node_id:
                        _res_sub(n.available, a.resources)
                self._wake_scheduler()
                return n.node_id
        return self.add_node(resources, labels)

    async def shutdown(self) -> None:
        self._closing = True
        for t in getattr(self, "_bcast_push_tasks", ()):  # in-flight chains
            t.cancel()
        self._snapshot_state()
        for w in list(self.workers.values()):
            try:
                await w.conn.send({"kind": "shutdown"})
            except Exception:
                pass
        for n in self.nodes.values():
            if n.agent_conn is not None:
                try:
                    await n.agent_conn.send({"kind": "shutdown"})
                except Exception:
                    pass
        await asyncio.sleep(0.05)
        for w in list(self.workers.values()):
            if w.proc is not None and w.proc.poll() is None:
                try:
                    w.proc.terminate()
                except Exception:
                    pass
        for loc in self.objects.values():
            if loc.host_id is not None and loc.host_id != self.host_id:
                continue  # remote bytes die with their agent's arena
            free_location(loc)
        self.objects.clear()
        from . import native_store

        native_store.close_arena(destroy=True)
        if self._sched_task is not None:
            self._sched_task.cancel()
        if self._health_task is not None:
            self._health_task.cancel()
        if getattr(self, "_memory_task", None) is not None:
            self._memory_task.cancel()
        if self._watchdog_task is not None:
            self._watchdog_task.cancel()
        if self._leak_task is not None:
            self._leak_task.cancel()
        if self._telemetry_task is not None:
            self._telemetry_task.cancel()
        if self.tsdb is not None:
            # Clean shutdown persists unconditionally (maybe_persist is
            # period-gated); a bounce resumes history where it stopped.
            self.tsdb.save(self.alerts.snapshot() if self.alerts else None)
        if getattr(self, "_metrics_server", None) is not None:
            self._metrics_server.close()
        if self.server is not None:
            self.server.close()

    async def _shutdown_worker(self, w: WorkerInfo) -> None:
        """Gracefully stop one worker process (already removed from pools)."""
        try:
            await w.conn.send({"kind": "shutdown"})
        except Exception:
            pass
        await asyncio.sleep(0.05)
        if w.proc is not None and w.proc.poll() is None:
            try:
                w.proc.terminate()
            except Exception:
                pass

    # ------------------------------------------------------- connection layer

    async def _on_connection(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        conn = protocol.Connection(reader, writer, self._handle, name="controller-peer")
        conn.start()
        await conn.closed.wait()
        await self._on_disconnect(conn)

    async def _on_disconnect(self, conn: protocol.Connection) -> None:
        if self._closing:
            return
        self.driver_conns.discard(conn)
        # A departing driver's worker leases: resources return, but the
        # workers are recycled (they may be executing orphaned pushes).
        for lid, lease in list(self._leases.items()):
            if lease["owner"] is conn:
                self._release_lease(lid, to_idle=False)
        for node in self.nodes.values():
            if node.agent_conn is conn:
                await self._on_node_death(node)
                return
        dead = [w for w in self.workers.values() if w.conn is conn]
        for w in dead:
            await self._on_worker_death(w)

    async def _on_node_death(self, node: NodeInfo) -> None:
        """Agent connection lost (or heartbeat timed out): the whole host is
        gone. Reference: GCS node-failure handling, gcs_node_manager.h —
        every worker and actor on the node dies with it."""
        if not node.alive:
            return
        node.alive = False
        node.suspect = False  # terminal: past suspicion
        if node.draining:
            # The node left while (or because) it was draining — a
            # preemption that fired before the grace window closed, or the
            # drain's own shutdown. Either way the departure was planned:
            # record it as drained so worker cleanup below re-queues work
            # through the budget-free preempted paths.
            node.draining = False
            node.drained = True
            self.pending_drains.pop(node.node_id, None)
            task = self._drain_tasks.pop(node.node_id, None)
            if task is not None and not task.done():
                task.cancel()
            self._state_dirty = True
        self._export_event("NODE", {"node_id": node.node_id,
                                    "event": "dead", "ts": time.time()})
        if node.drained:
            self._emit_event(
                "INFO", "NODE_REMOVED",
                f"node {node.node_id[:8]} left after draining "
                f"({node.drain_reason or 'drain'})",
                node_id=node.node_id,
                data={"reason": node.drain_reason})
        else:
            self._emit_event(
                "ERROR", "NODE_DIED",
                f"node {node.node_id[:8]} died "
                f"({len(node.workers)} worker(s) lost)",
                node_id=node.node_id,
                data={"workers": len(node.workers),
                      "host_id": node.host_id})
        node.agent_conn = None
        node.agent_addr = None
        for wid in list(node.workers):
            w = self.workers.get(wid)
            if w is not None:
                await self._on_worker_death(w)
                try:
                    await w.conn.close()
                except Exception:
                    pass
        node.workers.clear()
        node.spawning = 0
        node.spawning_tpu = 0
        for tok, nid in list(self._agent_spawns.items()):
            if nid == node.node_id:
                self._agent_spawns.pop(tok, None)
                self._tpu_spawn_tokens.discard(tok)
        # Replicas hosted on the dead host are gone; prune them first so
        # promotion below never hands out a dead copy.
        for oid, reps in list(self.object_replicas.items()):
            for nid in [k for k, r in reps.items()
                        if r.host_id == node.host_id]:
                reps.pop(nid, None)
            if not reps:
                self.object_replicas.pop(oid, None)
        # Objects whose bytes lived only on the dead host are lost. A
        # surviving broadcast replica is promoted to primary (no recompute,
        # no re-pull); else if the producing task's spec is in the lineage
        # table and its deps are still resolvable, re-execute it
        # (reference: object_recovery_manager.h ReconstructObject);
        # otherwise store a clear error so a later get() doesn't dial a
        # dead pull server.
        resubmitted: Set[str] = set()
        for oid, loc in list(self.objects.items()):
            if (
                loc.inline is None
                and loc.host_id is not None
                and loc.host_id == node.host_id
            ):
                if self._promote_replica(oid):
                    continue
                if self._maybe_reconstruct(oid, resubmitted):
                    continue
                lspec = self.lineage.get(oid)
                if lspec is None:
                    reason = "no lineage recorded"
                else:
                    reason = (f"reconstruction cap reached "
                              f"({lspec.get('_reconstructions', 0)}/"
                              f"{flags.get('RTPU_MAX_RECONSTRUCTIONS')})")
                self._emit_event(
                    "ERROR", "OBJECT_LOST",
                    f"object {oid[:8]} lost with node {node.node_id[:8]} "
                    f"({reason})",
                    node_id=node.node_id,
                    task_id=lspec["task_id"] if lspec else None,
                    data={"object_id": oid, "reason": reason,
                          "attempts": int(lspec.get("_reconstructions", 0))
                          if lspec else 0})
                self._store_error(
                    oid,
                    ObjectLostError(
                        f"object {oid[:8]} was lost when node "
                        f"{node.node_id[:8]} died"
                    ),
                )
        self._wake_scheduler()

    def _promote_replica(self, oid: str) -> bool:
        """Primary copy lost: promote a surviving broadcast replica to the
        object table so consumers (and lineage) never notice."""
        reps = self.object_replicas.get(oid)
        if not reps:
            return False
        for nid, rep in list(reps.items()):
            if self._host_alive(rep.host_id):
                reps.pop(nid, None)
                if not reps:
                    self.object_replicas.pop(oid, None)
                self.objects[oid] = rep
                return True
        return False

    def _maybe_reconstruct(self, oid: str, resubmitted: Set[str]) -> bool:
        """Resubmit the producing task of a lost object. Single-level: deps
        must still be present (a missing dep chain errors out rather than
        recursing)."""
        spec = self.lineage.get(oid)
        if spec is None:
            return False
        if spec["task_id"] in resubmitted:
            self.objects.pop(oid, None)  # resubmit already queued covers it
            return True
        if spec["task_id"] in self.tasks:
            self.objects.pop(oid, None)
            return True
        recon = int(spec.get("_reconstructions", 0))
        if recon >= flags.get("RTPU_MAX_RECONSTRUCTIONS"):
            return False
        for dep in spec.get("deps", []):
            loc = self.objects.get(dep)
            if loc is None:
                # Gone entirely: ok only if its producer is already being
                # re-run (the dep waiter picks up the new location); a freed
                # dep would stall the resubmit forever.
                dspec = self.lineage.get(dep)
                if dspec is None or (
                    dspec["task_id"] not in resubmitted
                    and dspec["task_id"] not in self.tasks
                ):
                    return False
            elif loc.is_error:
                return False
        spec["_reconstructions"] = recon + 1
        spec["state"] = "pending"
        spec.pop("sched_node", None)
        spec.pop("blocked", None)
        # Drop the stale locations so consumers re-wait on the new result.
        for rid in spec["return_ids"]:
            self.objects.pop(rid, None)
        resubmitted.add(spec["task_id"])
        self.tasks[spec["task_id"]] = spec
        self.pending_queue.append(spec)
        self._record_task_event(spec, "reconstruct")
        self._emit_event(
            "WARNING", "OBJECT_RECONSTRUCTING",
            f"object {oid[:8]} lost; re-executing producing task "
            f"{spec.get('label') or spec['task_id'][:8]} "
            f"(attempt {spec['_reconstructions']}/"
            f"{flags.get('RTPU_MAX_RECONSTRUCTIONS')})",
            task_id=spec["task_id"],
            data={"object_id": oid,
                  "attempt": spec["_reconstructions"],
                  "label": spec.get("label")})
        return True

    async def _on_worker_death(self, w: WorkerInfo) -> None:
        self.workers.pop(w.worker_id, None)
        # Flip hosted actors to restarting BEFORE the awaited post-mortem
        # fetch below: a call resubmitted in that window (the client's
        # recovery thread races the death handler) must buffer in
        # pending_calls, not observe an alive actor with no worker.
        for aid in list(w.actor_ids):
            _a = self.actors.get(aid)
            if _a is not None and _a.state == "alive":
                _a.state = "restarting"
        # Crash post-mortem (reference: worker-death exit_detail quoting
        # the crashed process's stderr in RayTaskError / ActorDiedError):
        # fetched only when the death actually fails user work.
        detail = ""
        _node = self.nodes.get(w.node_id)
        _planned = _node is not None and (_node.draining or _node.drained)
        if (w.current_task and w.current_task in self.tasks) or w.actor_ids:
            detail = await self._worker_exit_detail(w)
            if w.oom_killed:
                # Worker-OOM post-mortem (PR 3's log-tail fetch) as a
                # first-class cluster event: the kill decision, victim,
                # and the crashed process's last log lines in one record.
                self._emit_event(
                    "ERROR", "WORKER_OOM",
                    f"worker {w.worker_id[:8]} on node {w.node_id[:8]} "
                    f"was killed by the memory monitor while running "
                    f"{(self.tasks.get(w.current_task or '') or {}).get('label') or 'actor work'}",
                    worker_id=w.worker_id, node_id=w.node_id,
                    task_id=w.current_task,
                    data={"log_tail": detail.strip()})
            elif not _planned:
                self._emit_event(
                    "ERROR", "WORKER_DIED",
                    f"worker {w.worker_id[:8]} on node {w.node_id[:8]} "
                    f"died with work in flight",
                    worker_id=w.worker_id, node_id=w.node_id,
                    task_id=w.current_task,
                    data={"actors": len(w.actor_ids),
                          "log_tail": detail.strip()})
        node = self.nodes.get(w.node_id)
        if node:
            node.workers.discard(w.worker_id)
            if w.chip_ids and node.agent_conn is None:
                # Local-spawn pool only: agent-spawned workers' chips are
                # owned and recycled by their agent's reap loop.
                node.tpu_free.extend(w.chip_ids)
                w.chip_ids = []
        # A leased worker's death frees the lease's reserved resources; the
        # holder notices via its broken direct connection and resubmits
        # through the controller (tasks are retryable, unlike actor calls).
        for lid, lease in list(self._leases.items()):
            if lease["worker_id"] == w.worker_id:
                self._release_lease(lid)
        # Planned departure? A worker dying on a draining/drained node was
        # preempted, not crashed: its work re-queues without consuming
        # retry/restart budgets (reference: DrainNode graceful-departure
        # semantics vs node failure).
        preempted = node is not None and (node.draining or node.drained)
        # Fail — or retry — the running task (reference: task resubmission on
        # worker failure, core_worker/task_manager.h max_retries).
        if w.current_task and w.current_task in self.tasks:
            spec = self.tasks.pop(w.current_task)
            self._release_task_resources(spec)
            if preempted:
                err: Exception = NodePreemptedError(
                    f"worker {w.worker_id[:8]} left with draining node "
                    f"{w.node_id[:8]} "
                    f"({node.drain_reason or 'drain'}) while running task "
                    f"{spec.get('label', '')}")
            elif w.oom_killed:
                err = OutOfMemoryError(
                    f"worker {w.worker_id[:8]} was killed by the memory "
                    f"monitor while running task {spec.get('label', '')} "
                    f"(host memory pressure){detail}")
            else:
                err = WorkerCrashedError(
                    f"worker {w.worker_id[:8]} died while running task "
                    f"{spec.get('label', '')}{detail}")
            if not self._maybe_retry_task(spec, preempted=preempted):
                self._finalize_generator(spec["task_id"], err)
                for oid in spec["return_ids"]:
                    self._store_error(oid, err)
        # Restart or mark dead hosted actors.
        for aid in list(w.actor_ids):
            actor = self.actors.get(aid)
            if actor and actor.state != "dead":
                if preempted:
                    err = NodePreemptedError(
                        f"actor {aid[:8]} left with draining node "
                        f"{w.node_id[:8]} ({node.drain_reason or 'drain'})")
                else:
                    err = WorkerCrashedError(
                        f"actor {aid[:8]} process died{detail}")
                if not self._maybe_restart_actor(actor, err,
                                                 preempted=preempted):
                    self._mark_actor_dead(actor, err)
        self._wake_scheduler()

    async def _worker_exit_detail(self, w: WorkerInfo) -> str:
        """Bounded tail of a dead worker's log file, fetched from its host
        (the controller reads head-host files itself, agent hosts answer
        over their control connection) — so OOM-killed and segfaulted
        workers are attributable from the driver without SSH. Never fatal,
        never unbounded."""
        limit = int(flags.get("RTPU_EXIT_DETAIL_BYTES"))
        if not limit or not w.spawn_token:
            return ""
        from . import worker_logs as wl

        name = wl.log_file_name(w.spawn_token)
        node = self.nodes.get(w.node_id)
        try:
            if node is not None and node.agent_conn is not None:
                text = await node.agent_conn.request(
                    {"kind": "tail_log", "name": name, "bytes": limit},
                    timeout=3)
            else:
                text = await asyncio.to_thread(
                    wl.read_tail, os.path.join(wl.log_dir(), name), limit)
        except Exception:
            return ""
        text = (text or "").strip()
        if not text or text.startswith("<log unavailable"):
            return ""
        return (f"\n--- last log lines of the dead worker ({name}) ---\n"
                f"{text}")

    def _fail_env_tasks(self, env_hash: str, err: Exception) -> None:
        """A runtime env cannot materialize: every task queued for it would
        otherwise retry the broken install forever."""
        for tid in self.pending_queue.ids():
            spec = self.tasks.get(tid)
            if spec is not None and (spec.get("env_hash") or "") == env_hash:
                self.pending_queue.remove(tid)
                self._fail_task(
                    spec,
                    RuntimeEnvSetupError(f"runtime env setup failed: {err}"),
                )

    def _maybe_retry_task(self, spec: Dict[str, Any],
                          preempted: bool = False) -> bool:
        """Resubmit a task killed by a system failure (worker/node death),
        up to max_retries times. Application errors never retry here — they
        reach _h_task_done as error locations, not a dead connection.
        ``preempted`` (planned node departure): the task ALWAYS re-queues
        and the retry budget is untouched — the result was never observed,
        so replaying it is safe and free."""
        if spec.get("is_actor_creation") or spec.get("actor_id"):
            return False
        retries = int(spec.get("max_retries", 0))
        used = int(spec.get("_retry_count", 0))
        if not preempted and used >= retries:
            return False
        if spec.get("streaming") and spec["task_id"] in self.generators:
            gen = self.generators[spec["task_id"]]
            if gen.items:
                # Items already observed by the consumer can't be replayed
                # consistently; only an unstarted stream retries.
                return False
        if not preempted:
            spec["_retry_count"] = used + 1
        spec["state"] = "pending"
        spec.pop("sched_node", None)
        spec.pop("blocked", None)
        spec.pop("__dispatch_ts", None)
        self.tasks[spec["task_id"]] = spec
        self.pending_queue.append(spec)
        self._record_task_event(spec, "retry")
        if preempted:
            self._emit_event(
                "WARNING", "TASK_PREEMPTED",
                f"task {spec.get('label') or spec['task_id'][:8]} "
                f"re-queued after planned node departure "
                f"(no retry budget consumed)",
                task_id=spec["task_id"],
                data={"label": spec.get("label")})
        else:
            self._emit_event(
                "WARNING", "TASK_RETRY",
                f"task {spec.get('label') or spec['task_id'][:8]} "
                f"re-queued after worker/node failure "
                f"(retry {spec.get('_retry_count', 0)}/"
                f"{spec.get('max_retries', 0)})",
                task_id=spec["task_id"],
                data={"label": spec.get("label"),
                      "retry": spec.get("_retry_count", 0)})
        self._wake_scheduler()
        return True

    def _maybe_restart_actor(self, actor: ActorInfo, err: Exception,
                             preempted: bool = False) -> bool:
        """Re-instantiate a crashed actor from its creation spec (reference:
        gcs_actor_manager RestartActor, max_restarts semantics). In-flight
        calls fail (at-most-once actor tasks); calls submitted while
        restarting buffer and replay on actor_ready. ``preempted``
        (planned node departure): detached/restartable actors re-create
        without consuming restart budget."""
        spec = actor.creation_spec
        if spec is None:
            return False
        if preempted:
            if not (actor.detached
                    or actor.restart_count < actor.max_restarts):
                return False
        elif actor.restart_count >= actor.max_restarts:
            return False
        # Restore the newest reachable state instead of re-running the
        # constructor. An UNCONSUMED migration/restore blob in the spec
        # wins: it is popped at actor_ready, so its presence proves the
        # restored instance never confirmed — never mutated past the
        # snapshot, and always at least as new as the last checkpoint
        # (previously the crash path dropped it here, silently losing
        # migrated state when the restore target died between dispatch
        # and actor_ready). Otherwise the newest durable checkpoint — its
        # record carries the exactly-once journal, so replayed calls
        # dedup against everything it covers.
        if spec.get("state_blob") is None and actor.checkpoint is not None \
                and actor.checkpoint.get("blob") is not None:
            spec["state_blob"] = actor.checkpoint["blob"]
        if not preempted:
            actor.restart_count += 1
        actor.state = "restarting"
        self._export_event("ACTOR", {"actor_id": actor.actor_id,
                                     "event": "restarting",
                                     "ts": time.time()})
        self._emit_event(
            "WARNING", "ACTOR_RESTARTING",
            f"actor {actor.name or actor.actor_id[:8]} restarting after "
            f"{'preemption' if preempted else 'crash'}: {err} "
            f"(restart {actor.restart_count}/{actor.max_restarts})",
            actor_id=actor.actor_id, node_id=actor.node_id,
            worker_id=actor.worker_id,
            data={"cause": f"{type(err).__name__}: {err}",
                  "preempted": preempted,
                  "restarts": actor.restart_count})
        from .job_manager import SUPERVISOR_PREFIX

        if (actor.name or "").startswith(SUPERVISOR_PREFIX):
            # Job supervisor going around the restart loop: record the
            # pending attempt cause (preempted restarts bill no job
            # budget) and sweep the orphaned entrypoint process group.
            self.jobs.note_supervisor_died(actor, err, preempted,
                                           fatal=False)
        # Fail calls already forwarded to the dead worker — but NOT calls
        # still buffered in pending_calls (never dispatched): those replay
        # after restart, and erroring them here would double-signal.
        # Replay-enabled calls (max_task_retries actors) re-buffer instead
        # of failing: the restored actor's journal short-circuits any that
        # actually executed, so redelivery is exactly-once, not at-least.
        buffered = {p["task_id"] for p in actor.pending_calls}
        for tid, t in list(self.tasks.items()):
            if (
                t.get("actor_id") == actor.actor_id
                and not t.get("is_actor_creation")
                and tid not in buffered
            ):
                if t.get("replay"):
                    t.pop("sched_node", None)
                    t.pop("__dispatch_ts", None)
                    actor.pending_calls.append(t)
                else:
                    self._fail_task(t, err)
        node = self.nodes.get(actor.node_id or "")
        if node and actor.reserved:
            actor.reserved = False
            self._release_reservation(actor.resources, node, actor.pg)
        actor.worker_id = None
        actor.node_id = None
        spec["state"] = "pending"
        spec.pop("sched_node", None)
        self.tasks[spec["task_id"]] = spec
        self.pending_queue.append(spec)
        self._record_task_event(spec, "actor_restart")
        self._wake_scheduler()
        return True

    # ------------------------------------------------------------ msg routing

    async def _handle(self, conn: protocol.Connection, msg: Dict[str, Any]) -> Any:
        kind = msg["kind"]
        fn = getattr(self, f"_h_{kind}", None)
        if fn is None:
            raise ValueError(f"controller: unknown message kind {kind!r}")
        # Per-kind message counter: observability (dashboard /metrics) and
        # the ownership-protocol tests' proof that ref passing between
        # workers makes NO controller round-trips.
        self.rpc_counts[kind] = self.rpc_counts.get(kind, 0) + 1
        return await fn(conn, msg)

    # --------------------------------------------------------------- handlers

    async def _h_register(self, conn, msg):
        role = msg["role"]
        if role == "driver":
            self.driver_conns.add(conn)
            return {"ok": True, "controller_host_id": self.host_id}
        worker_id = msg["worker_id"]
        node_id = msg["node_id"]
        reconnect = bool(msg.get("reconnect"))
        node = self.nodes.get(node_id)
        w = self.workers.get(worker_id)
        if reconnect and w is None and node is None:
            # The worker outlived a controller restart but its node hasn't
            # (re-)registered yet — its host agent may still be dialing.
            # Ask the worker to retry instead of adopting it onto a node
            # the scheduler doesn't know (reconcile, don't trust blindly).
            return {"ok": False, "retry": True}
        adopted = reconnect and w is None
        if w is not None:
            w.conn = conn  # reconnect
            w.direct_port = int(msg.get("direct_port") or 0)
            w.pid = int(msg.get("pid") or 0)
        else:
            w = WorkerInfo(worker_id=worker_id, node_id=node_id, conn=conn,
                           tpu_capable=bool(msg.get("tpu_capable")),
                           env_hash=msg.get("env_hash") or "",
                           pid=int(msg.get("pid") or 0),
                           direct_port=int(msg.get("direct_port") or 0))
            self.workers[worker_id] = w
        # Exact proc adoption via startup token (reference: worker startup
        # tokens, worker_pool.h:251) — heuristic matching can swap proc handles
        # between workers, making kill() terminate the wrong process.
        token = msg.get("spawn_token")
        was_tpu_spawn = False
        if token:
            proc = self._spawned_procs.pop(token, None)
            if proc is not None:
                w.proc = proc
            else:
                self._agent_spawns.pop(token, None)  # no longer outstanding
            # Kept for BOTH spawn flavors: names the worker's log file for
            # the cluster log index (kill routing still checks proc first).
            w.spawn_token = token
            from .worker_logs import log_file_name

            self.worker_log_names[worker_id] = {
                "node_id": node_id, "name": log_file_name(token)}
            self.worker_log_names.move_to_end(worker_id)
            while len(self.worker_log_names) > 8192:
                self.worker_log_names.popitem(last=False)
            was_tpu_spawn = token in self._tpu_spawn_tokens
            self._tpu_spawn_tokens.discard(token)
            # Local spawns: adopt the controller-side allocation (also
            # removes it from the never-registered-exit path). Agent
            # spawns: the agent allocated; trust the worker's report.
            # Non-TPU workers never hold chips regardless of env noise.
            w.chip_ids = (self._chip_alloc.pop(token, None)
                          or list(msg.get("chip_ids") or [])) \
                if w.tpu_capable else []
        if node:
            node.workers.add(worker_id)
            if not reconnect:
                node.spawning = max(0, node.spawning - 1)
                if was_tpu_spawn:
                    node.spawning_tpu = max(0, node.spawning_tpu - 1)
                if token:
                    self._release_env_spawn(node, token)
            elif adopted and w.chip_ids and node.agent_conn is None:
                # Chip reconciliation on re-register after a controller
                # restart: the restored node's free pool starts full, and
                # this worker's grant must leave it — free-pool and granted
                # sets stay disjoint (no chip double-allocation).
                taken = set(w.chip_ids)
                node.tpu_free = [c for c in node.tpu_free if c not in taken]
        if reconnect:
            # Re-claim plain tasks still executing on the re-registering
            # worker (reference: the GCS rebuilding lease state from raylet
            # re-reports on failover). The driver resubmits in-flight specs
            # on ITS reconnect — without this claim the controller would
            # both schedule the duplicate AND consider the worker idle
            # (breaking drain's quiesce wait); with it, the running
            # instance finishes and its task_done retires the spec.
            for tid in msg.get("running") or ():
                spec = self.tasks.get(tid)
                if spec is not None and spec.get("actor_id"):
                    continue  # actor calls are claimed via msg["actors"]
                if spec is not None and not spec.get("sched_node"):
                    self.pending_queue.remove(tid)
                    spec["state"] = "running"
                    spec["sched_node"] = None  # resources never reserved
                w.current_task = tid
                if w.state == "idle":
                    w.state = "task"
                break
        drop = await self._adopt_worker_actors(w, node, msg)
        self._wake_scheduler()
        return {"ok": True, "drop_actors": drop}

    async def _adopt_worker_actors(
        self, w: WorkerInfo, node: Optional[NodeInfo], msg: Dict[str, Any]
    ) -> List[str]:
        """Reconcile actors a re-registering worker claims to host
        (reference: gcs_actor_manager rebuilding the actor directory from
        worker re-reports on GCS failover). The live instance wins over a
        queued re-creation; a re-creation already dispatched (or finished)
        elsewhere wins over the stale claimant, which is told to drop it."""
        drop: List[str] = []
        adopted: List[ActorInfo] = []
        for aspec in msg.get("actors") or ():
            aid = aspec["actor_id"]
            actor = self.actors.get(aid)
            if actor is None:
                # Non-detached actor (not persisted): rebuild the directory
                # entry from the worker's report. No creation spec — a later
                # crash of this worker kills the actor for good.
                actor = ActorInfo(
                    actor_id=aid,
                    name=aspec.get("name"),
                    resources=dict(aspec.get("resources") or {}),
                    detached=bool(aspec.get("detached")),
                    max_restarts=int(aspec.get("max_restarts", 0)),
                )
                self.actors[aid] = actor
                if aspec.get("name"):
                    key = (aspec.get("namespace", "default"), aspec["name"])
                    cur = self.named_actors.get(key)
                    if cur is None or self.actors[cur].state == "dead":
                        self.named_actors[key] = aid
            if actor.state == "dead":
                drop.append(aid)
                continue
            if actor.state == "alive" and actor.worker_id not in (
                    None, w.worker_id):
                drop.append(aid)  # already re-created elsewhere
                continue
            ctid = actor.creation_task_id
            cspec = self.tasks.get(ctid) if ctid else None
            if cspec is not None:
                if cspec.get("sched_node"):
                    # Re-creation already dispatched: that instance wins.
                    drop.append(aid)
                    continue
                # Still queued: cancel it — the live instance keeps serving
                # with its state intact (the whole point of adoption).
                self.tasks.pop(ctid, None)
                self.pending_queue.remove(ctid)
            actor.worker_id = w.worker_id
            actor.node_id = w.node_id
            w.actor_ids.add(aid)
            w.state = "actor"
            if node is not None and not actor.reserved and actor.pg is None:
                _res_sub(node.available, actor.resources)
                actor.reserved = True
            adopted.append(actor)
        for actor in adopted:
            # Same drain-before-alive ordering as _h_actor_ready: queued
            # calls dispatch before the direct address is handed out.
            while actor.pending_calls:
                calls, actor.pending_calls = actor.pending_calls, []
                for call in calls:
                    await self._dispatch_actor_call(actor, call)
            actor.state = "alive"
            self._export_event("ACTOR", {"actor_id": actor.actor_id,
                                         "event": "adopted",
                                         "name": actor.name,
                                         "node_id": actor.node_id,
                                         "ts": time.time()})
            self._emit_event(
                "INFO", "ACTOR_ADOPTED",
                f"actor {actor.name or actor.actor_id[:8]} re-claimed by "
                f"its surviving worker after a controller bounce",
                actor_id=actor.actor_id, node_id=actor.node_id,
                worker_id=actor.worker_id, data={"name": actor.name})
        return drop

    def _release_env_spawn(self, node: Optional[NodeInfo], token: str) -> None:
        eh = self._spawn_env_hash.pop(token, None)
        if eh and node is not None and node.spawning_envs.get(eh, 0) > 0:
            node.spawning_envs[eh] -= 1
            if not node.spawning_envs[eh]:
                node.spawning_envs.pop(eh, None)

    async def _h_metric_update(self, conn, msg):
        """App-metric deltas from workers/drivers (util/metrics.py;
        reference python/ray/util/metrics.py -> metrics_agent). Counters
        accumulate, gauges overwrite, histogram observations bucket-count
        against the metric's boundaries."""
        for m in msg.get("metrics", []):
            name = m["name"]
            st = self.app_metrics.setdefault(
                name, {"type": m["type"], "help": m.get("help", ""),
                       "boundaries": m.get("boundaries") or [],
                       "data": {}})
            for tags_list, value in m.get("data", []):
                tags = tuple(tuple(t) for t in tags_list)
                if m["type"] == "gauge":
                    st["data"][tags] = value
                elif m["type"] == "counter":
                    st["data"][tags] = st["data"].get(tags, 0.0) + value
                else:  # histogram: per-tag {bucket_counts, sum, count}
                    h = st["data"].setdefault(
                        tags, {"buckets": [0] * (len(st["boundaries"]) + 1),
                               "sum": 0.0, "count": 0})
                    if isinstance(value, dict):
                        # Pre-aggregated bucket counts (util/metrics.py
                        # aggregates at record time): merge elementwise,
                        # overflow into the +Inf bucket on length mismatch.
                        for i, c in enumerate(value.get("buckets", ())):
                            if c:
                                h["buckets"][min(i, len(h["buckets"]) - 1)] \
                                    += c
                        h["sum"] += value.get("sum", 0.0)
                        h["count"] += value.get("count", 0)
                        continue
                    for obs in value:  # legacy raw observation list
                        i = 0
                        for i, b in enumerate(st["boundaries"]):
                            if obs <= b:
                                break
                        else:
                            i = len(st["boundaries"])
                        h["buckets"][i] += 1
                        h["sum"] += obs
                        h["count"] += 1
        return {"ok": True}

    async def _h_worker_log(self, conn, msg):
        """Forward a worker's stdout/stderr line to every connected driver
        (reference: _private/log_monitor.py tailing worker logs to the
        driver). Fire-and-forget fanout; a dead driver conn is skipped."""
        out = {"kind": "log", "line": msg.get("line", ""),
               "pid": msg.get("pid"), "worker_id": msg.get("worker_id", ""),
               "stream": msg.get("stream", "stdout")}
        for dconn in list(self.driver_conns):
            try:
                # Drop lines to a stalled driver rather than queueing them:
                # logs are lossy-by-contract, controller memory is not.
                if (dconn.writer.transport.get_write_buffer_size()
                        > 1 << 20):
                    continue
                dconn._buffered_write(dconn._frame(out))
            except Exception:
                pass
        return None

    async def _h_put_location(self, conn, msg):
        loc: ObjectLocation = msg["loc"]
        if msg.get("if_absent") and loc.object_id in self.objects:
            # Direct-dispatch failure reports must not clobber a real
            # result the worker managed to deliver before dying.
            return {"ok": True}
        self._store_location(loc)
        # Leak watchdog: remember who registered the object — a put whose
        # connection later closes while the object lingers past
        # RTPU_LEAK_AGE_S is a leak suspect (only the put path records a
        # source; unattributed objects are never flagged — safe direction).
        self.object_src.setdefault(loc.object_id, conn)
        return {"ok": True}

    async def _wait_for_object(self, oid: str, deadline: Optional[float] = None) -> ObjectLocation:
        """Block until `oid` is in the object table; waiter registrations are
        cleaned up on timeout/cancel so polling callers don't leak Events."""
        while oid not in self.objects:
            ev = asyncio.Event()
            lst = self.object_waiters.setdefault(oid, [])
            lst.append(ev)
            try:
                if deadline is None:
                    await ev.wait()
                else:
                    remaining = max(0.0, deadline - time.monotonic())
                    await asyncio.wait_for(ev.wait(), remaining or 1e-6)
            finally:
                if not ev.is_set():
                    try:
                        lst.remove(ev)
                    except ValueError:
                        pass
                    if not lst:
                        self.object_waiters.pop(oid, None)
        return self.objects[oid]

    async def _h_get_locations(self, conn, msg):
        ids: List[str] = msg["object_ids"]
        timeout = msg.get("timeout")
        owners: Dict[str, str] = msg.get("owners") or {}
        # Consumer node (when the requester reports it): replica-aware
        # resolution hands back the copy local to that host, so a
        # broadcast object is read over shm instead of re-pulled.
        req_node = msg.get("node_id")
        deadline = None if timeout is None else time.monotonic() + timeout
        out: Dict[str, ObjectLocation] = {}
        now = time.monotonic()
        for oid in ids:
            if oid not in self.objects and owners.get(oid):
                # Directory miss with a known owner: the owner is the
                # authority for its objects (reference ownership protocol —
                # the GCS directory is a cache, owners are truth). Covers
                # registration races and directory loss across a controller
                # restart.
                await self._owner_locate(oid, owners[oid])
            try:
                loc = await self._wait_for_object(oid, deadline)
                out[oid] = self._replica_view(oid, loc, req_node)
                self.object_touch[oid] = now
            except asyncio.TimeoutError:
                raise GetTimeoutError(f"object {oid[:8]} not ready within {timeout}s") from None
        return out

    async def _owner_locate(self, oid: str, owner_addr: str) -> None:
        hostport = owner_addr.partition("|")[0]
        host, _, port = hostport.rpartition(":")
        try:
            conn = await protocol.connect(host, int(port), name="owner-locate")
            try:
                res = await conn.request({"kind": "ref_locate", "oid": oid},
                                         timeout=2)
            finally:
                await conn.close()
            loc = (res or {}).get("loc")
            if loc is not None and oid not in self.objects:
                self._store_location(loc)
        except Exception:
            pass  # owner gone/unreachable: fall through to the normal wait

    async def _h_rpc_stats(self, conn, msg):
        return dict(self.rpc_counts)

    async def _h_worker_logs(self, conn, msg):
        """Legacy list/tail of worker log files on one host (the original
        dashboard viewer contract: a list of names, or one tail string).
        The cluster-wide surface is list_logs / resolve_log / get_log."""
        import os as _os

        from .worker_logs import log_dir, list_log_files, read_tail

        node_id = msg.get("node_id") or ""
        name = msg.get("name")
        node = self.nodes.get(node_id)
        if node is not None and node.agent_conn is not None:
            try:
                if name:
                    return await node.agent_conn.request(
                        {"kind": "tail_log", "name": name,
                         "bytes": msg.get("bytes", 65536)}, timeout=10)
                res = await node.agent_conn.request(
                    {"kind": "list_logs"}, timeout=10)
                return [f["name"] if isinstance(f, dict) else f
                        for f in res]
            except Exception as e:
                return f"<agent unavailable: {e}>" if name else []
        # Local (controller-spawned workers).
        if not name:
            return [f["name"] for f in list_log_files()]
        safe = _os.path.basename(name)
        nbytes = min(int(msg.get("bytes", 65536)), 1 << 20)
        try:
            return read_tail(_os.path.join(log_dir(), safe), nbytes)
        except OSError as e:
            return f"<log unavailable: {e}>"

    # -------------------------------------------------- cluster log subsystem
    # Reference: the `ray logs` CLI + dashboard log API — any log file on
    # any node is listable and fetchable through the head, with task/actor
    # attribution resolving an id to the owning host's file.

    async def _h_list_logs(self, conn, msg):
        """Cluster log index: node_id -> [{name, size, mtime}] for every
        alive node (agent hosts answer over their control connection; the
        controller lists the head host itself)."""
        out: Dict[str, Any] = {}
        local: Optional[List[Dict[str, Any]]] = None
        for node in list(self.nodes.values()):
            if not node.alive:
                continue
            if node.agent_conn is not None:
                try:
                    out[node.node_id] = await node.agent_conn.request(
                        {"kind": "list_logs"}, timeout=5)
                except Exception:
                    out[node.node_id] = []
            else:
                if local is None:
                    from .worker_logs import list_log_files

                    local = list_log_files()
                out[node.node_id] = local
        return out

    def _resolve_log_target(self, msg) -> Optional[Dict[str, str]]:
        """task/actor/worker id -> {node_id, name} of the log file the
        owning worker writes (the attribution the cluster log index keeps
        beyond worker death)."""
        wid = msg.get("worker_id")
        if not wid and msg.get("actor_id"):
            a = self.actors.get(msg["actor_id"])
            wid = a.worker_id if a is not None else None
        if not wid and msg.get("task_id"):
            tid = msg["task_id"]
            for ev in reversed(self.task_events):
                if ev.get("task_id") == tid and ev.get("worker_id"):
                    wid = ev["worker_id"]
                    break
        if not wid:
            return None
        return self.worker_log_names.get(wid)

    async def _h_resolve_log(self, conn, msg):
        t = self._resolve_log_target(msg)
        if t is None:
            return {"found": False}
        return {"found": True, **t}

    async def _h_get_log(self, conn, msg):
        """Fetch a chunk of one worker log from whichever host owns it
        (offset/max_bytes ranged; task_id/actor_id filters to attributed
        output via the sidecar index; wait_s long-polls for follow mode).
        Ids resolve on every call, so a follow stream re-resolves cleanly
        after a controller bounce rebuilt the index from re-registers."""
        m = {k: msg.get(k) for k in
             ("name", "node_id", "offset", "max_bytes", "task_id",
              "actor_id", "worker_id", "wait_s", "strip_markers")
             if msg.get(k) is not None}
        if not m.get("name"):
            t = self._resolve_log_target(m)
            if t is None:
                return {"error": "no log file known for that id",
                        "data": "", "offset": int(m.get("offset") or 0),
                        "size": 0, "eof": True}
            m["name"] = t["name"]
            m["node_id"] = t["node_id"]
        return await self._fetch_log(m)

    async def _fetch_log(self, m: Dict[str, Any]) -> Dict[str, Any]:
        """Route one ranged log read to the owning host agent (or serve
        locally for head-host/virtual-node files). Shared by _h_get_log
        and the job-log walker, which follows a job's output across
        supervisor failovers file by file."""
        node = self.nodes.get(m.get("node_id") or "")
        if node is not None and node.agent_conn is not None:
            try:
                return await node.agent_conn.request(
                    {"kind": "get_log", **m},
                    timeout=float(m.get("wait_s") or 0) + 10)
            except Exception as e:
                return {"error": f"agent unavailable: {e!r}", "data": "",
                        "offset": int(m.get("offset") or 0), "size": 0,
                        "eof": True}
        from .worker_logs import serve_get_log_wait

        return await serve_get_log_wait(m)

    # jobs (core/job_manager.py) ----------------------------------------------
    # Thin delegates: the job table, attempt protocol, and log walker all
    # live in JobManager; these exist so `_handle` dispatch finds them.

    async def _h_job_submit(self, conn, msg):
        return self.jobs.submit(msg)

    async def _h_job_attempt_start(self, conn, msg):
        return await self.jobs.attempt_start(msg)

    async def _h_job_exec(self, conn, msg):
        return self.jobs.attempt_exec(msg)

    async def _h_job_attempt_done(self, conn, msg):
        return self.jobs.attempt_done(msg)

    async def _h_job_status(self, conn, msg):
        return self.jobs.status(msg.get("job_id") or "")

    async def _h_job_list(self, conn, msg):
        return {"jobs": self.jobs.list()}

    async def _h_job_wait(self, conn, msg):
        return await self.jobs.wait(msg)

    async def _h_job_stop(self, conn, msg):
        return await self.jobs.stop(msg)

    async def _h_job_stop_ack(self, conn, msg):
        return self.jobs.stop_ack(msg)

    async def _h_job_logs(self, conn, msg):
        return await self.jobs.logs(msg)

    async def _h_wait(self, conn, msg):
        """O(n) wait: one callback registration per missing object, arrivals
        drained incrementally (the previous design re-registered a waiter
        future for every not-ready id on every wake — O(n^2) registrations
        for large batches; reference envelope is a 10k-object wait,
        release/benchmarks/README.md)."""
        ids: List[str] = msg["object_ids"]
        num_returns: int = msg["num_returns"]
        timeout = msg.get("timeout")
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[str] = []
        missing: List[str] = []
        for oid in ids:
            (ready if oid in self.objects else missing).append(oid)
        if len(ready) >= num_returns:
            return ready[:num_returns]
        arrived: List[str] = []
        wake = asyncio.Event()

        def notify(oid: str) -> None:
            arrived.append(oid)
            wake.set()

        for oid in missing:
            self.object_callbacks.setdefault(oid, []).append(notify)
        def drain() -> None:
            ready.extend(arrived)
            arrived.clear()

        try:
            while True:
                if deadline is None:
                    await wake.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        drain()  # arrivals that raced the deadline count
                        return ready[:num_returns]
                    try:
                        await asyncio.wait_for(wake.wait(), remaining)
                    except asyncio.TimeoutError:
                        drain()
                        return ready[:num_returns]
                wake.clear()
                drain()
                if len(ready) >= num_returns:
                    return ready[:num_returns]
        finally:
            for oid in missing:
                cbs = self.object_callbacks.get(oid)
                if cbs is not None:
                    try:
                        cbs.remove(notify)
                    except ValueError:
                        pass
                    if not cbs:
                        self.object_callbacks.pop(oid, None)

    async def _h_free_objects(self, conn, msg):
        for oid in msg["object_ids"]:
            loc = self.objects.pop(oid, None)
            self.object_touch.pop(oid, None)
            self.object_created.pop(oid, None)
            self.object_src.pop(oid, None)
            self._leak_reported.discard(oid)
            # Broadcast replicas die with the primary: each copy frees on
            # its own host (same routing as the primary's bytes).
            reps = self.object_replicas.pop(oid, None)
            for rep in (reps or {}).values():
                await self._free_one_location(rep)
            if loc is None:
                continue
            await self._free_one_location(loc)
        return {"ok": True}

    async def _free_one_location(self, loc: ObjectLocation) -> None:
        if loc.host_id is not None and loc.host_id != self.host_id:
            # Bytes live on another host: route the free to its agent.
            node = self.nodes.get(loc.node_id or "")
            if node is not None and node.agent_conn is not None:
                try:
                    await node.agent_conn.send(
                        {"kind": "free_object", "loc": loc})
                except Exception:
                    pass
            return
        free_location(loc)

    async def _h_register_function(self, conn, msg):
        self.functions[msg["func_id"]] = msg["blob"]
        self._state_dirty = True
        return {"ok": True}

    async def _h_fetch_function(self, conn, msg):
        blob = self.functions.get(msg["func_id"])
        if blob is None:
            raise KeyError(f"function {msg['func_id']} not found in function table")
        return blob

    def _record_task_event(self, spec, event: str, **extra) -> None:
        ev = {
            "task_id": spec.get("task_id"),
            "label": spec.get("label"),
            "actor_id": spec.get("actor_id"),
            "event": event,
            "ts": time.time(),
            "worker_id": extra.get("worker_id") or spec.get("_worker_id"),
            "node_id": extra.get("node_id") or spec.get("sched_node"),
        }
        self.task_events.append(ev)
        self._export_event("TASK", ev)

    def _export_event(self, source: str, payload: Dict[str, Any]) -> None:
        """Structured export-event pipeline (reference: src/ray/util/event.h
        RAY_EVENT + the export-event JSONL files external systems tail):
        when RTPU_EVENT_EXPORT_PATH is set, every control-plane event
        appends one {source_type, timestamp, event_data} JSON line. Opened
        lazily, line-buffered; failures disable export rather than touch
        the control plane."""
        path = flags.get("RTPU_EVENT_EXPORT_PATH")
        if not path:
            return
        f = getattr(self, "_export_file", None)
        if f is None:
            try:
                f = self._export_file = open(path, "a", buffering=1)
            except OSError:
                self._export_file = False
                return
        if f is False:
            return
        try:
            f.write(json.dumps({
                "source_type": source,
                "timestamp": payload.get("ts") or time.time(),
                "event_data": {k: v for k, v in payload.items()
                               if k != "ts"},
            }, default=str) + "\n")
        except Exception:
            self._export_file = False

    async def _h_submit_task(self, conn, msg):
        spec = msg["spec"]
        # Idempotent by task id (partition hardening): a blind re-send
        # after an RPC timeout — or a driver-reconnect resubmission racing
        # a controller that never actually lost the first copy — must not
        # double-schedule.
        tid = spec["task_id"]
        if tid in self.tasks:
            return {"ok": True, "dup": True}
        rids = spec.get("return_ids") or ()
        if rids and all(r in self.objects for r in rids):
            return {"ok": True, "dup": True}
        self.tasks[spec["task_id"]] = spec
        self._note_child(spec)
        spec["state"] = "waiting_deps"
        if spec.get("streaming"):
            self.generators[spec["task_id"]] = GeneratorState(
                task_id=spec["task_id"],
                window=int(spec.get("backpressure", 16)),
            )
        self._record_task_event(spec, "submitted")
        await self._resolve_deps_then_queue(spec)
        return {"ok": True}

    # streaming generators ----------------------------------------------------

    async def _h_generator_item(self, conn, msg):
        """Producer reports one yielded item (reference:
        ReportGeneratorItemReturns, core_worker.proto:462). The reply is
        withheld while the consumer lags more than the backpressure window,
        which stalls the producing worker thread — flow control without a
        second channel."""
        gen = self.generators.get(msg["task_id"])
        self._store_location(msg["loc"])
        if gen is None:
            return {"ok": True}
        gen.items.append(msg["loc"].object_id)
        gen.wake.set()
        while (
            len(gen.items) - gen.consumed > gen.window
            and not gen.done
            and not gen.closed
        ):
            gen.drain.clear()
            await gen.drain.wait()
        return {"ok": True, "closed": gen.closed}

    async def _h_generator_next(self, conn, msg):
        """Consumer requests item `index`; blocks until produced, raises the
        task's error, or reports exhaustion."""
        gen = self.generators.get(msg["task_id"])
        if gen is None:
            raise ValueError(f"unknown streaming task {msg['task_id'][:8]}")
        index = msg["index"]
        while True:
            if index < len(gen.items):
                gen.consumed = max(gen.consumed, index + 1)
                gen.drain.set()
                return {"object_id": gen.items[index]}
            if gen.error is not None:
                self.generators.pop(msg["task_id"], None)
                raise gen.error
            if gen.done:
                self.generators.pop(msg["task_id"], None)
                return {"done": True}
            gen.wake.clear()
            await gen.wake.wait()

    async def _h_generator_close(self, conn, msg):
        """Consumer dropped the generator: release a producer stalled in the
        backpressure wait and let state be reclaimed (reference: streaming
        generator cancellation on deleted ObjectRefGenerator)."""
        gen = self.generators.get(msg["task_id"])
        if gen is None:
            return {"ok": True}
        gen.closed = True
        gen.drain.set()
        gen.wake.set()
        if gen.done:
            self.generators.pop(msg["task_id"], None)
        return {"ok": True}

    async def _resolve_deps_then_queue(self, spec: Dict[str, Any]) -> None:
        deps: List[str] = [d for d in spec.get("deps", []) if d not in self.objects]
        if deps:
            async def waiter():
                for oid in list(deps):
                    await self._wait_for_object(oid)
                # Dependency errors propagate without running the task.
                err = self._first_dep_error(spec)
                if err is not None:
                    self._fail_task(spec, err)
                    return
                spec["state"] = "pending"
                self.pending_queue.append(spec)
                self._wake_scheduler()

            asyncio.get_running_loop().create_task(waiter())
        else:
            err = self._first_dep_error(spec)
            if err is not None:
                self._fail_task(spec, err)
                return
            spec["state"] = "pending"
            self.pending_queue.append(spec)
            self._wake_scheduler()

    def _first_dep_error(self, spec) -> Optional[Exception]:
        for oid in spec.get("deps", []):
            loc = self.objects.get(oid)
            if loc is not None and loc.is_error:
                return DependencyError(f"upstream task failed for object {oid[:8]}")
        return None

    def _note_child(self, spec: Dict[str, Any]) -> None:
        ptid = spec.get("parent_task_id")
        if not ptid:
            return
        # Hard cap: a pathological fan-out must not let the tree outgrow
        # the task table it mirrors.
        if len(self.task_parent) > 4 * self.lineage_max:
            return
        self.task_children.setdefault(ptid, set()).add(spec["task_id"])
        self.task_parent[spec["task_id"]] = ptid

    async def _h_task_lineage(self, conn, msg):
        """Fire-and-forget ownership note for directly-pushed child tasks
        (the controller never sees their submission): parent -> child edges
        feeding the recursive-cancel tree."""
        for parent, child in msg.get("edges") or ():
            if parent and child and len(self.task_parent) <= 4 * self.lineage_max:
                self.task_children.setdefault(parent, set()).add(child)
                self.task_parent[child] = parent
        return {"ok": True}

    def _prune_child(self, task_id: str) -> None:
        ptid = self.task_parent.pop(task_id, None)
        if ptid is None:
            return
        kids = self.task_children.get(ptid)
        if kids is not None:
            kids.discard(task_id)
            if not kids:
                self.task_children.pop(ptid, None)

    def _fail_task(self, spec, err: Exception) -> None:
        self.tasks.pop(spec["task_id"], None)
        self._prune_child(spec["task_id"])
        self._record_task_event(spec, "failed")
        self._finalize_generator(spec["task_id"], err)
        for oid in spec["return_ids"]:
            self._store_error(oid, err)

    def _finalize_generator(self, task_id: str, err: Optional[Exception]) -> None:
        gen = self.generators.get(task_id)
        if gen is not None and not gen.done:
            gen.error = gen.error or err
            gen.done = True
            gen.wake.set()
            gen.drain.set()

    async def _h_cancel_task(self, conn, msg):
        """ray.cancel (reference: python/ray/_private/worker.py cancel +
        CancelTask RPC): a QUEUED task is failed in place with
        TaskCancelledError — no worker round-trip; a RUNNING one gets an
        async-raise in its executing thread (force=True kills the worker
        process instead — for code that swallows exceptions). An actor
        call's cancel removes the still-queued spec or interrupts the
        hosting worker's mailbox entry. recursive=True additionally walks
        the ownership tree and cancels every live descendant. Every path
        is idempotent: double-cancel and cancel-of-finished are no-ops."""
        force = bool(msg.get("force"))
        recursive = bool(msg.get("recursive"))
        oid = msg.get("object_id")
        task_id = msg.get("task_id")
        spec = None
        if task_id is not None:
            spec = self.tasks.get(task_id)
        if spec is None and oid is not None:
            for t in self.tasks.values():
                if oid in (t.get("return_ids") or ()):
                    spec = t
                    task_id = t["task_id"]
                    break
        if spec is None and task_id is None and oid is not None:
            # Finished parent: resolve the subtree root from the bounded
            # done-oid map so recursive still reaches live descendants.
            task_id = self.done_oid2task.get(oid)
        if spec is None and oid is not None and oid in self.objects \
                and not (recursive and task_id):
            # Already finished: a cancel is a no-op, not an error.
            return {"ok": True, "state": "finished"}
        if spec is None and not (recursive and task_id):
            return {"ok": False, "reason": "unknown or already finished"}
        state = await self._cancel_one(spec, force) or "finished"
        descendants = 0
        if recursive and task_id:
            seen = {task_id}
            frontier = list(self.task_children.get(task_id, ()))
            while frontier:
                child = frontier.pop()
                if child in seen:
                    continue
                seen.add(child)
                frontier.extend(self.task_children.get(child, ()))
                cspec = self.tasks.get(child)
                if cspec is not None:
                    if await self._cancel_one(cspec, force):
                        descendants += 1
                elif child in self.task_parent:
                    # A live edge but no controller-side spec: the child
                    # was pushed directly to a leased worker. Broadcast the
                    # mark — its host refuses it at dequeue or async-raises
                    # the running thread; everyone else ignores it.
                    await self._broadcast_cancel(child)
                    descendants += 1
        return {"ok": True, "state": state, "descendants": descendants}

    async def _cancel_one(self, spec, force: bool) -> Optional[str]:
        """Cancel a single live spec; returns the resulting state, or None
        when there was nothing to do."""
        if spec is None:
            return None
        task_id = spec["task_id"]
        if spec.get("__cancelled__"):
            return "already_cancelled"
        if spec.get("actor_id"):
            actor = self.actors.get(spec["actor_id"])
            spec["__cancelled__"] = True
            spec["max_retries"] = 0
            if actor is not None and spec in actor.pending_calls:
                try:
                    actor.pending_calls.remove(spec)
                except ValueError:
                    pass
                self._fail_task(spec, TaskCancelledError(
                    f"actor call {task_id[:8]} was cancelled before it started"))
                self._record_task_event(spec, "cancelled")
                return "queued"
            w = self.workers.get(actor.worker_id or "") if actor else None
            if w is None:
                return "marked"
            # The hosting worker either refuses the mailbox entry at
            # dequeue or async-raises the running call. force degrades to
            # the async-raise: killing the worker would take the whole
            # actor (that is rtpu.kill's job).
            try:
                await w.conn.send({"kind": "cancel_task", "task_id": task_id})
            except Exception:
                pass
            self._record_task_event(spec, "cancel_requested",
                                    worker_id=w.worker_id)
            return "running"
        w = next((x for x in self.workers.values()
                  if x.current_task == task_id), None)
        if w is None:
            # Still queued: remove + fail the returns at the controller.
            self.pending_queue.remove(task_id)
            self._release_task_resources(spec)
            self._fail_task(spec, TaskCancelledError(
                f"task {task_id[:8]} was cancelled before it started"))
            self._record_task_event(spec, "cancelled")
            return "queued"
        spec["max_retries"] = 0  # a cancel must not resurrect it
        spec["__cancelled__"] = True
        if force:
            await self._shutdown_worker(w)
            return "force_killed"
        try:
            await w.conn.send({"kind": "cancel_task", "task_id": task_id})
        except Exception:
            pass
        self._record_task_event(spec, "cancel_requested",
                                worker_id=w.worker_id)
        return "running"

    async def _broadcast_cancel(self, task_id: str) -> None:
        for w in list(self.workers.values()):
            try:
                await w.conn.send({"kind": "cancel_task", "task_id": task_id})
            except Exception:
                pass

    async def _h_task_spillback(self, conn, msg):
        """A worker's admission check rejected a dispatched task
        (reference: raylet spillback — the scheduler retries elsewhere
        with the rejecting node excluded). Resources are returned, the
        worker goes back to idle, and the spec re-queues."""
        task_id = msg["task_id"]
        spec = self.tasks.get(task_id)
        w = self.workers.get(msg.get("worker_id", ""))
        if w is not None and w.current_task == task_id:
            w.current_task = None
            if w.state == "task":
                w.state = "idle"
        if spec is None:
            return {"ok": False}
        self._release_task_resources(spec)
        node_id = spec.pop("sched_node", None)
        spec.pop("blocked", None)
        if node_id:
            spec.setdefault("spillback_excluded", []).append(node_id)
        spec["spillback_count"] = spec.get("spillback_count", 0) + 1
        spec["state"] = "waiting_deps"
        self._record_task_event(spec, "spillback",
                                worker_id=msg.get("worker_id"),
                                node_id=node_id)
        await self._resolve_deps_then_queue(spec)
        self._wake_scheduler()
        return {"ok": True}

    async def _h_task_done(self, conn, msg):
        task_id = msg["task_id"]
        gen = self.generators.get(task_id)
        if gen is not None:
            if msg.get("is_error") or msg.get("error_locations"):
                err_locs = msg.get("error_locations") or []
                if err_locs:
                    import pickle as _p

                    try:
                        gen.error = _p.loads(err_locs[0].inline)
                    except Exception:
                        gen.error = WorkerCrashedError("streaming task failed")
                else:
                    gen.error = WorkerCrashedError("streaming task failed")
            gen.done = True
            gen.wake.set()
            gen.drain.set()
            if gen.closed:
                self.generators.pop(task_id, None)
        spec = self.tasks.pop(task_id, None)
        # retry_exceptions (reference: @ray.remote(retry_exceptions=True),
        # task_manager.cc RetryTask on application error): a failed task
        # with retry budget re-queues instead of surfacing the error —
        # cancelled tasks excepted (a cancel must stick).
        if (spec is not None and msg.get("is_error")
                and spec.get("retry_exceptions")
                and int(spec.get("max_retries", 0)) > 0
                and not spec.get("__cancelled__")
                and not gen):
            spec["max_retries"] = int(spec["max_retries"]) - 1
            if w := self.workers.get(msg["worker_id"]):
                if w.current_task == task_id:
                    w.current_task = None
                    if w.state == "task":
                        w.state = "idle"
            self._release_task_resources(spec)
            spec.pop("sched_node", None)
            spec.pop("blocked", None)
            spec["state"] = "waiting_deps"
            self.tasks[task_id] = spec
            self._record_task_event(spec, "retry",
                                    worker_id=msg.get("worker_id"))
            await self._resolve_deps_then_queue(spec)
            self._wake_scheduler()
            return {"ok": True}
        self._prune_child(task_id)
        if spec is not None:
            for oid in spec.get("return_ids") or ():
                self.done_oid2task[oid] = task_id
            while len(self.done_oid2task) > 4 * self.lineage_max:
                self.done_oid2task.popitem(last=False)
            self._record_task_event(
                spec, "failed" if msg.get("is_error") else "finished",
                worker_id=msg.get("worker_id"))
        for loc in msg.get("locations", []):
            self._store_location(loc)
        if msg.get("error_locations"):
            for loc in msg["error_locations"]:
                self._store_location(loc)
        w = self.workers.get(msg["worker_id"])
        if w is not None:
            # It delivered a result: the memory-monitor kill (if any) did
            # not take — a later unrelated death must not be blamed on OOM.
            w.oom_killed = False
        if w is not None and w.current_task == task_id:
            w.current_task = None
            if w.state == "task":
                w.state = "idle"
        if spec is not None:
            self._release_task_resources(spec)
            self._record_lineage(spec, msg)
        elif msg.get("spec") is not None:
            # Directly-pushed (leased) task: the controller never saw the
            # submission, so the completion report carries the spec — enough
            # to register lineage (object reconstruction after node loss)
            # and the task events. Resources stay pinned by the lease. The
            # worker's start timestamp synthesizes the "running" event the
            # timeline pairs with the terminal one.
            for oid in msg["spec"].get("return_ids") or ():
                # Leased tasks resolve through done_oid2task too: without
                # this, a recursive cancel rooted at a FINISHED direct-push
                # parent cannot find the subtree.
                self.done_oid2task[oid] = msg["spec"].get("task_id", task_id)
            while len(self.done_oid2task) > 4 * self.lineage_max:
                self.done_oid2task.popitem(last=False)
            if msg.get("started_ts"):
                w_lease = self.workers.get(msg.get("worker_id", ""))
                self.task_events.append({
                    "task_id": msg["spec"].get("task_id"),
                    "label": msg["spec"].get("label"),
                    "actor_id": None,
                    "event": "running",
                    "ts": msg["started_ts"],
                    "worker_id": msg.get("worker_id"),
                    "node_id": w_lease.node_id if w_lease else None,
                })
            self._record_task_event(
                msg["spec"], "failed" if msg.get("is_error") else "finished",
                worker_id=msg.get("worker_id"))
            self._record_lineage(msg["spec"], msg)
        self._wake_scheduler()
        return {"ok": True}

    async def _h_task_done_batch(self, conn, msg):
        """Multi-entry completion report: one framed message carries many
        task_done payloads (acks + result-location publishes) shipped by a
        worker's completion batcher — one unpickle and one handler pass for
        a whole burst of finishes (reference: CoreWorker's batched task
        status/export reports riding one gRPC call)."""
        for item in msg.get("items") or ():
            await self._h_task_done(conn, item)
        return {"ok": True}

    def _record_lineage(self, spec: Dict[str, Any], msg: Dict[str, Any]) -> None:
        """Remember the spec of a successfully finished plain task so its
        outputs can be reconstructed after a node loss."""
        if (
            msg.get("is_error")
            or msg.get("error_locations")
            or spec.get("actor_id")
            or spec.get("is_actor_creation")
            or spec.get("streaming")
            or not spec.get("return_ids")
            # Slim leased-completion reports (inline-only results carry
            # their bytes in the stored location) have no func_id — there
            # is nothing to re-execute and nothing that can be lost.
            or not spec.get("func_id")
        ):
            return
        for oid in spec["return_ids"]:
            self.lineage[oid] = spec
            self.lineage.move_to_end(oid)
        while len(self.lineage) > self.lineage_max:
            self.lineage.popitem(last=False)

    async def _h_task_blocked(self, conn, msg):
        # A task blocked in get() releases its CPU so child tasks can run
        # (reference: NotifyDirectCallTaskBlocked, raylet_client.h:380).
        spec = self.tasks.get(msg["task_id"])
        if spec is not None and not spec.get("blocked"):
            spec["blocked"] = True
            node = self.nodes.get(spec.get("sched_node", ""))
            cpu = spec.get("resources", {}).get("CPU", 0.0)
            if node and cpu:
                _res_add(node.available, {"CPU": cpu})
                self._wake_scheduler()
        return {"ok": True}

    async def _h_task_unblocked(self, conn, msg):
        spec = self.tasks.get(msg["task_id"])
        if spec is not None and spec.get("blocked"):
            spec["blocked"] = False
            node = self.nodes.get(spec.get("sched_node", ""))
            cpu = spec.get("resources", {}).get("CPU", 0.0)
            if node and cpu:
                # May drive available negative transiently; oversubscription on
                # wake avoids deadlock (same tradeoff the reference makes).
                _res_sub(node.available, {"CPU": cpu})
        return {"ok": True}

    # actors ------------------------------------------------------------------

    async def _h_create_actor(self, conn, msg):
        spec = msg["spec"]
        actor_id = spec["actor_id"]
        if actor_id in self.actors:
            # Idempotent by actor id (partition hardening): a retried
            # create after an RPC timeout joins the original creation.
            return {"ok": True, "dup": True}
        name = spec.get("name")
        namespace = spec.get("namespace", "default")
        if name:
            key = (namespace, name)
            if key in self.named_actors and self.actors[self.named_actors[key]].state != "dead":
                raise ValueError(f"actor name {name!r} already taken")
            self.named_actors[key] = actor_id
        actor = ActorInfo(
            actor_id=actor_id,
            name=name,
            resources=spec.get("resources", {}),
            pg=spec.get("pg"),
            detached=spec.get("detached", False),
            creation_task_id=spec["task_id"],
            max_restarts=int(spec.get("max_restarts", 0)),
            creation_spec=spec,
        )
        self.actors[actor_id] = actor
        if actor.detached:
            self._state_dirty = True
        self._emit_event(
            "INFO", "ACTOR_CREATED",
            f"actor {name or actor_id[:8]} creation submitted"
            + (" (detached)" if actor.detached else ""),
            actor_id=actor_id,
            data={"name": name, "detached": actor.detached})
        spec["is_actor_creation"] = True
        self.tasks[spec["task_id"]] = spec
        await self._resolve_deps_then_queue(spec)
        return {"ok": True}

    async def _h_actor_ready(self, conn, msg):
        actor = self.actors.get(msg["actor_id"])
        if actor is None:
            return {"ok": False}
        # Stale-sender guard: an actor_ready that raced the sender's death
        # (e.g. delayed in flight while the worker was killed and the
        # restart already re-queued the creation) must not flip a
        # restarting actor alive — the restart path owns it now, and the
        # consumed-blob pop below would discard state the re-queued
        # creation still needs.
        sender = next((w for w in self.workers.values() if w.conn is conn),
                      None)
        if actor.worker_id is None or (
                sender is not None and sender.worker_id != actor.worker_id):
            return {"ok": False, "stale": True}
        if actor.creation_task_id:
            spec = self.tasks.pop(actor.creation_task_id, None)
            if spec is not None:
                self._record_task_event(spec, "finished")
        # Drain queued calls BEFORE flipping to alive: resolve_actor must
        # not hand out the direct address while controller-queued calls are
        # still being dispatched, or a fresh direct call could overtake them
        # at the worker (per-caller ordering). Dispatch awaits, so new
        # submissions can interleave and re-append — hence the loop.
        while actor.pending_calls:
            calls, actor.pending_calls = actor.pending_calls, []
            for call in calls:
                await self._dispatch_actor_call(actor, call)
        actor.state = "alive"
        # The restore is CONFIRMED (the worker loaded the record before
        # sending actor_ready): the blob is consumed now — the instance
        # mutates from here on, so a later crash re-creation must restore
        # from a durable checkpoint (or the constructor), never this copy.
        # Until this point the blob stays in the spec, so a restore target
        # dying between dispatch and actor_ready retries with state intact.
        if actor.creation_spec is not None:
            actor.creation_spec.pop("state_blob", None)
        if msg.get("restored_epoch") is not None:
            self._emit_event(
                "INFO", "ACTOR_RESTORED",
                f"actor {actor.name or actor.actor_id[:8]} restored from "
                f"checkpoint epoch {msg['restored_epoch']} on node "
                f"{(actor.node_id or '?')[:8]}",
                actor_id=actor.actor_id, node_id=actor.node_id,
                worker_id=actor.worker_id,
                data={"epoch": int(msg["restored_epoch"])})
        self._export_event("ACTOR", {"actor_id": actor.actor_id,
                                     "event": "alive", "name": actor.name,
                                     "node_id": actor.node_id,
                                     "ts": time.time()})
        self._emit_event(
            "INFO", "ACTOR_ALIVE",
            f"actor {actor.name or actor.actor_id[:8]} alive on node "
            f"{(actor.node_id or '?')[:8]}",
            actor_id=actor.actor_id, node_id=actor.node_id,
            worker_id=actor.worker_id, data={"name": actor.name})
        return {"ok": True}

    async def _h_actor_exit(self, conn, msg):
        """Intentional actor termination via exit_actor: dead WITHOUT
        restart regardless of max_restarts (reference semantics)."""
        actor = self.actors.get(msg["actor_id"])
        if actor is None:
            return {"ok": False}
        actor.max_restarts = 0  # an intentional exit must stick
        self._mark_actor_dead(actor, ActorDiedError(
            f"actor {actor.actor_id[:8]} exited via exit_actor()"))
        w = self.workers.get(actor.worker_id or "")
        if w is not None:
            w.actor_ids.discard(actor.actor_id)
            if not w.actor_ids:
                w.state = "idle"
        self._export_event("ACTOR", {"actor_id": actor.actor_id,
                                     "event": "exited",
                                     "ts": time.time()})
        self._wake_scheduler()
        return {"ok": True}

    async def _h_actor_error(self, conn, msg):
        actor = self.actors.get(msg["actor_id"])
        if actor is None:
            return {"ok": False}
        if actor.creation_task_id:
            spec = self.tasks.pop(actor.creation_task_id, None)
            if spec is not None:
                self._record_task_event(spec, "failed")
        actor.creation_error = msg["error"]
        self._mark_actor_dead(actor, msg["error"])
        w = self.workers.get(actor.worker_id or "")
        if w is not None:
            w.actor_ids.discard(actor.actor_id)
            if not w.actor_ids:
                w.state = "idle"
        self._wake_scheduler()
        return {"ok": True}

    def _store_actor_checkpoint(self, actor: ActorInfo, epoch: int,
                                blob: bytes) -> bool:
        """Record one shipped checkpoint (newest epoch wins; duplicates and
        stragglers are dropped). Detached actors additionally persist the
        record next to --state-path so it survives a controller bounce."""
        epoch = int(epoch)
        cur = actor.checkpoint
        if cur is not None and cur["epoch"] >= epoch:
            return False
        actor.checkpoint = {"epoch": epoch, "blob": blob,
                            "bytes": len(blob), "ts": time.time()}
        self.ckpt_stats["count"] += 1
        self.ckpt_stats["bytes"] += len(blob)
        if actor.detached and self.persist_path:
            # 8-byte big-endian epoch header + opaque record: the restore
            # path reads the epoch without unpickling user state into the
            # controller process.
            import struct as _struct

            path = f"{self.persist_path}.ckpt.{actor.actor_id}"
            tmp = path + f".tmp{os.getpid()}"
            try:
                with open(tmp, "wb") as f:
                    f.write(_struct.pack("!Q", epoch) + blob)
                os.replace(tmp, path)
            except OSError:
                pass
        self._emit_event(
            "DEBUG", "ACTOR_CHECKPOINTED",
            f"actor {actor.name or actor.actor_id[:8]} checkpointed "
            f"(epoch {epoch}, {len(blob)} bytes)",
            actor_id=actor.actor_id, node_id=actor.node_id,
            worker_id=actor.worker_id,
            data={"epoch": epoch, "bytes": len(blob)})
        return True

    async def _h_actor_checkpoint(self, conn, msg):
        """Async copy of a worker's durable actor checkpoint (the host-
        local file is the fast copy; this one survives whole-node loss)."""
        actor = self.actors.get(msg["actor_id"])
        if actor is None or actor.state == "dead":
            return None
        self._store_actor_checkpoint(actor, msg["epoch"], msg["blob"])
        return None

    async def _h_submit_actor_task(self, conn, msg):
        spec = msg["spec"]
        # Idempotent by task id (partition hardening): a timed-out-and-
        # retried submit whose original landed must not run twice — known
        # in-flight specs and already-published results answer ok.
        tid = spec["task_id"]
        if tid in self.tasks:
            return {"ok": True, "dup": True}
        rids = spec.get("return_ids") or ()
        if rids and all(r in self.objects for r in rids):
            return {"ok": True, "dup": True}
        actor = self.actors.get(spec["actor_id"])
        if actor is None:
            raise ValueError(f"unknown actor {spec['actor_id']}")
        if spec.get("streaming"):
            self.generators[spec["task_id"]] = GeneratorState(
                task_id=spec["task_id"],
                window=int(spec.get("backpressure", 16)),
            )
        if actor.state == "dead":
            err = actor.creation_error or ActorDiedError(f"actor {actor.actor_id[:8]} is dead")
            self._finalize_generator(spec["task_id"], err)
            for oid in spec["return_ids"]:
                self._store_error(oid, err)
            return {"ok": True}
        self.tasks[spec["task_id"]] = spec
        self._note_child(spec)
        if actor.state in ("pending", "restarting"):
            actor.pending_calls.append(spec)
        else:
            await self._dispatch_actor_call(actor, spec)
        return {"ok": True}

    async def _dispatch_actor_call(self, actor: ActorInfo, spec: Dict[str, Any]) -> None:
        dl = spec.get("deadline_ts")
        if dl is not None and time.time() > dl:
            # Expired while parked in pending_calls (or on arrival): the
            # mailbox never sees dead work.
            self._fail_task(spec, DeadlineExceededError(
                f"actor call {spec['task_id'][:8]} deadline passed while queued"))
            self._record_task_event(spec, "deadline_exceeded")
            return
        w = self.workers.get(actor.worker_id or "")
        if w is None:
            if spec.get("replay") and actor.state != "dead":
                # Worker death mid-handling: a replayable call parks and
                # redelivers after the restart (journal dedups).
                actor.pending_calls.append(spec)
            else:
                self._fail_task(spec, ActorDiedError("actor worker gone"))
            return
        node = self.nodes.get(actor.node_id or "")
        if node is not None and node.suspect:
            # Suspect host (heartbeat-silent, possibly partitioned): a
            # fire-and-forget dispatch there would vanish. Buffer — the
            # heal path flushes in order; the death path re-buffers or
            # fails per the actor's replay setting.
            actor.pending_calls.append(spec)
            return
        # Per-actor ordered dispatch (direct_actor_task_submitter.h sequencing).
        async with actor.order_lock:
            # Wait for deps before forwarding so the worker never blocks.
            for oid in spec.get("deps", []):
                await self._wait_for_object(oid)
            err = self._first_dep_error(spec)
            if err is not None:
                self._fail_task(spec, err)
                return
            spec["sched_node"] = actor.node_id
            spec["__dispatch_ts"] = time.time()  # hang-watchdog age base
            self._record_task_event(spec, "running", worker_id=w.worker_id,
                                    node_id=actor.node_id)
            await w.conn.send({"kind": "execute_actor_task", "spec": spec})

    # ---- worker leases for direct task dispatch -----------------------------
    # Reference: direct_task_transport.h:75 — the owner leases a worker from
    # the raylet, then pushes tasks to it directly; the lease pins the
    # worker's resources until returned. Controller keeps directory/health/
    # lineage; the per-call path is peer-to-peer.

    def _grant_one_lease(self, conn, resources: Dict[str, float],
                         env_hash: str, arg_bytes: Dict[str, int],
                         block_id: str = "") -> Optional[Dict[str, Any]]:
        """One lease grant against current availability; None when no node
        can serve it. Shared by the single-lease and lease-block handlers —
        a block grant is just this loop run N times against the availability
        it is itself decrementing."""
        needs_tpu = resources.get("TPU", 0) > 0
        mem_limit = flags.get("RTPU_SPILLBACK_MEM_FRACTION")
        candidates = [n for n in self.nodes.values()
                      if self._schedulable(n)]
        for node in self._hybrid_order(candidates, arg_bytes):
            if not _res_fits(node.available, resources):
                continue
            # Grant-time admission for the direct path (the spillback
            # analog — pushed tasks never pass the worker's execute_task
            # check, so screen the node's reported memory pressure here).
            if mem_limit and node.mem_fraction >= mem_limit:
                self.lease_stats["mem_refused"] += 1
                continue
            # Server-side lease bound (advisor r4): once a node already
            # holds a lease, never lease away its LAST schedulable CPU.
            # Multiple drivers can otherwise collectively pin every idle
            # worker, leaving queued actor creations dependent solely on
            # the holder-cooperative, 0.2s-throttled reclaim nudge. (A
            # node's FIRST lease may still take the last CPU so tiny test
            # hosts keep direct dispatch; CPU-less requests can't take the
            # last CPU, so the guard doesn't apply to them.)
            req_cpu = resources.get("CPU", 0.0)
            has_lease = any(l["node_id"] == node.node_id
                            for l in self._leases.values())
            if (has_lease and req_cpu > 0
                    and node.available.get("CPU", 0.0) - req_cpu < 1.0):
                continue
            w = self._find_idle_worker(node, needs_tpu, env_hash,
                                       tpu_chips=int(resources.get("TPU", 0)))
            if w is None or not w.direct_port:
                continue
            _res_sub(node.available, resources)
            w.state = "leased"
            lease_id = uuid.uuid4().hex[:12]
            self._leases[lease_id] = {"worker_id": w.worker_id,
                                      "node_id": node.node_id,
                                      "resources": dict(resources),
                                      "block_id": block_id,
                                      "owner": conn}
            self.lease_stats["granted"] += 1
            peer = w.conn.writer.get_extra_info("peername")
            host = peer[0] if peer else "127.0.0.1"
            return {"lease_id": lease_id, "worker_id": w.worker_id,
                    "host": host, "port": w.direct_port,
                    "node_id": node.node_id}
        return None

    def _nudge_lease_spawns(self, resources: Dict[str, float],
                            runtime_env, arg_bytes: Dict[str, int],
                            count: int = 1) -> None:
        """Nothing idle: nudge spawns so a later lease request can succeed —
        in the SAME locality order as grants, so "grow toward the data
        node" creates the worker where the bytes are."""
        needs_tpu = resources.get("TPU", 0) > 0
        candidates = [n for n in self.nodes.values()
                      if self._schedulable(n)]
        for node in self._hybrid_order(candidates, arg_bytes):
            if count <= 0:
                break
            if _res_fits(node.available, resources):
                self._maybe_spawn_worker(node, needs_tpu, runtime_env,
                                         tpu_chips=int(resources.get("TPU", 0)))
                count -= 1

    async def _h_lease_worker(self, conn, msg):
        """Grant an idle worker to the requesting driver for direct task
        pushes. Returns {lease_id, worker_id, host, port} or {lease_id:
        None} when nothing is available (caller falls back to the queued
        controller path, which can also spawn new workers)."""
        resources: Dict[str, float] = msg.get("resources") or {"CPU": 1.0}
        # Locality term for the DIRECT path: the driver ships the byte
        # placement of the task's (cached-location) args so lease grants
        # rank nodes the same way queue placement does.
        arg_bytes: Dict[str, int] = msg.get("arg_bytes") or {}
        got = self._grant_one_lease(conn, resources,
                                    msg.get("env_hash") or "", arg_bytes)
        if got is not None:
            return got
        self._nudge_lease_spawns(resources, msg.get("runtime_env"),
                                 arg_bytes)
        return {"lease_id": None}

    async def _h_lease_block(self, conn, msg):
        """Bulk lease negotiation: grant up to ``count`` workers for one
        (resources, env) signature in a single round trip (reference: the
        raylet's lease tables keyed by scheduling class — the owner asks
        once per class, not once per worker, direct_task_transport.h:75).
        The driver fans its submission wave across the returned block with
        zero further controller involvement; partial grants are normal
        (the driver spills the remainder back through the queued path) and
        a shortfall nudges spawns so the next negotiation finds workers."""
        resources: Dict[str, float] = msg.get("resources") or {"CPU": 1.0}
        env_hash = msg.get("env_hash") or ""
        arg_bytes: Dict[str, int] = msg.get("arg_bytes") or {}
        count = max(1, int(msg.get("count", 1)))
        block_id = uuid.uuid4().hex[:12]
        grants: List[Dict[str, Any]] = []
        while len(grants) < count:
            got = self._grant_one_lease(conn, resources, env_hash,
                                        arg_bytes, block_id=block_id)
            if got is None:
                break
            grants.append(got)
        if grants:
            self.lease_stats["blocks"] += 1
        else:
            # Spawn nudges only on an EMPTY grant: a partial block means
            # the cluster is resource-saturated for this signature, where
            # a speculative spawn would burn ~50ms in this handler and
            # produce a worker the lease guard cannot grant anyway.
            self._nudge_lease_spawns(resources, msg.get("runtime_env"),
                                     arg_bytes)
        return {"block_id": block_id if grants else None, "grants": grants}

    def _release_lease(self, lease_id: str, to_idle: bool = True) -> None:
        """to_idle=False: the holder vanished without draining (driver
        disconnect) — the worker may still be executing an orphaned pushed
        task, so it is recycled rather than re-leased/scheduled (marking it
        idle would double-book its CPU)."""
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        node = self.nodes.get(lease["node_id"])
        if node is not None and node.alive:
            _res_add(node.available, lease["resources"])
        w = self.workers.get(lease["worker_id"])
        if w is not None and w.state == "leased":
            if to_idle:
                w.state = "idle"
            else:
                w.state = "dying"
                asyncio.get_running_loop().create_task(
                    self._shutdown_worker(w))
        self._wake_scheduler()

    async def _h_release_lease(self, conn, msg):
        # Accepts one lease_id or a lease_ids list (a block released in one
        # framed message — pool shutdown / reclaim hand back N at once).
        for lid in (msg.get("lease_ids") or
                    ([msg["lease_id"]] if msg.get("lease_id") else [])):
            self._release_lease(lid)
        return {"ok": True}

    async def _h_resolve_actor(self, conn, msg):
        """Lease-resolution for direct dispatch: where does this actor live?

        Callers resolve once, cache, and push calls straight to the worker's
        direct server (reference: direct_actor_task_submitter.h:74 — the
        submitter caches the actor's rpc address from the GCS and pushes).
        """
        actor = self.actors.get(msg["actor_id"])
        if actor is None:
            raise ValueError(f"unknown actor {msg['actor_id']}")
        # A just-created actor is usually mid-instantiation on its worker:
        # wait briefly for aliveness so the FIRST call can already go
        # direct (the caller pays instantiation latency either way).
        deadline = time.monotonic() + float(msg.get("wait", 1.0))
        while actor.state == "pending" and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        w = self.workers.get(actor.worker_id or "")
        direct = None
        if actor.state == "alive" and w is not None and w.direct_port:
            peer = w.conn.writer.get_extra_info("peername")
            host = peer[0] if peer else "127.0.0.1"
            # node_id lets callers decide locality (compiled-DAG edges
            # choose shm rings for same-node hops, streams otherwise).
            direct = {"worker_id": w.worker_id, "host": host,
                      "port": w.direct_port, "node_id": w.node_id}
        return {"state": actor.state, "direct": direct,
                "restarts": actor.restart_count}

    async def _h_dag_compiled(self, conn, msg):
        """A driver compiled a channel-based DAG: record the plan shape so
        `rtpu status` / state.list_state can show what pipelines hold
        resident loops on which actors. Steady-state execution never calls
        here — this pair of RPCs (with dag_torndown) is the controller's
        ENTIRE involvement in a compiled DAG's lifetime."""
        self.compiled_dags[msg["dag_id"]] = {
            "dag_id": msg["dag_id"],
            "stages": msg.get("stages", []),
            "edges": msg.get("edges", {}),
            "depth": msg.get("depth", 0),
            "since": time.time(),
        }
        return {"ok": True}

    async def _h_dag_torndown(self, conn, msg):
        self.compiled_dags.pop(msg["dag_id"], None)
        return {"ok": True}

    async def _h_dag_recovery(self, conn, msg):
        """A driver's self-healing pipeline reports a recovery phase
        transition (participant died / rebuilding / resumed / gave up).
        Bookkeeping + events only — the healing itself is driver-driven."""
        dag_id = msg["dag_id"]
        phase = msg.get("phase")
        d = self.compiled_dags.get(dag_id)
        if d is not None:
            if phase == "died":
                d["recovering"] = True
            elif phase == "recovering":
                d["recovering"] = True
            elif phase == "recovered":
                d["recovering"] = False
                d["recoveries"] = int(d.get("recoveries", 0)) + 1
                d["last_recovery_s"] = float(msg.get("duration_s", 0.0))
                d["last_cause"] = msg.get("cause")
            elif phase == "failed":
                d["recovering"] = False
                d["recovery_failures"] = (
                    int(d.get("recovery_failures", 0)) + 1)
        actors = msg.get("actors") or []
        short = ",".join(a[:8] for a in actors) or "?"
        cause = msg.get("cause", "?")
        if phase == "died":
            self._emit_event(
                "WARNING", "DAG_PARTICIPANT_DIED",
                f"compiled DAG {dag_id[:8]}: stage actor(s) {short} died "
                f"({cause}); pausing pipeline for in-place recovery")
        elif phase == "recovering":
            self._emit_event(
                "INFO", "DAG_RECOVERING",
                f"compiled DAG {dag_id[:8]}: quiescing survivors, "
                f"restarting {short}, rebuilding affected channels")
        elif phase == "recovered":
            # data= carries the structured cause so `rtpu events --kind
            # DAG_RECOVERED` can surface last_cause without parsing the
            # human message.
            self._emit_event(
                "INFO", "DAG_RECOVERED",
                f"compiled DAG {dag_id[:8]}: recovered from {cause} in "
                f"{float(msg.get('duration_s', 0.0)):.2f}s "
                f"(stage actor(s) {short} restarted, channels rebuilt, "
                f"retained items replayed)",
                data={"dag_id": dag_id, "cause": cause,
                      "actors": list(actors),
                      "duration_s": float(msg.get("duration_s", 0.0))})
        elif phase == "failed":
            self._emit_event(
                "ERROR", "DAG_RECOVERY_FAILED",
                f"compiled DAG {dag_id[:8]}: recovery from {cause} "
                f"failed; tearing the pipeline down")
        return {"ok": True}

    async def _h_get_named_actor(self, conn, msg):
        key = (msg.get("namespace", "default"), msg["name"])
        aid = self.named_actors.get(key)
        if aid is None or self.actors[aid].state == "dead":
            raise ValueError(f"no actor named {msg['name']!r}")
        actor = self.actors[aid]
        return {"actor_id": aid, "methods": self.kv.get(("__actor_methods__", aid), b"")}

    async def _h_kill_actor(self, conn, msg):
        actor = self.actors.get(msg["actor_id"])
        if actor is None or actor.state == "dead":
            return {"ok": True}
        w = self.workers.get(actor.worker_id or "")
        self._mark_actor_dead(actor, ActorDiedError(f"actor {actor.actor_id[:8]} was killed"))
        if w is not None:
            try:
                await w.conn.send({"kind": "shutdown"})
            except Exception:
                pass
            if w.proc is not None:
                try:
                    w.proc.terminate()
                except Exception:
                    pass
            elif w.spawn_token is not None:
                node = self.nodes.get(w.node_id)
                if node is not None and node.agent_conn is not None:
                    try:
                        await node.agent_conn.send(
                            {"kind": "kill_worker", "spawn_token": w.spawn_token}
                        )
                    except Exception:
                        pass
            await self._on_worker_death(w)
        return {"ok": True}

    def _mark_actor_dead(self, actor: ActorInfo, err: Exception) -> None:
        actor.state = "dead"
        actor.checkpoint = None  # retired for good: nothing may restore it
        if actor.detached and self.persist_path:
            try:
                os.unlink(f"{self.persist_path}.ckpt.{actor.actor_id}")
            except OSError:
                pass
        self._export_event("ACTOR", {"actor_id": actor.actor_id,
                                     "event": "dead", "ts": time.time()})
        self._emit_event(
            "ERROR", "ACTOR_DIED",
            f"actor {actor.name or actor.actor_id[:8]} died: {err}",
            actor_id=actor.actor_id, node_id=actor.node_id,
            worker_id=actor.worker_id,
            data={"name": actor.name, "cause": f"{type(err).__name__}: "
                  f"{err}", "restarts": actor.restart_count})
        if actor.detached:
            self._state_dirty = True
        from .job_manager import SUPERVISOR_PREFIX

        if (actor.name or "").startswith(SUPERVISOR_PREFIX):
            # Supervisor permanently dead (restart budget gone / actor
            # dropped): the job can never run again — fail it now so
            # wait_job callers don't hang on a supervisor that will
            # never report attempt_done.
            self.jobs.note_supervisor_died(actor, err, preempted=False,
                                           fatal=True)
        actor.creation_error = actor.creation_error or err
        for call in actor.pending_calls:
            self._fail_task(call, err)
        actor.pending_calls = []
        # Fail in-flight calls already forwarded to the worker.
        for tid, spec in list(self.tasks.items()):
            if spec.get("actor_id") == actor.actor_id:
                self._fail_task(spec, err)
        node = self.nodes.get(actor.node_id or "")
        if node and actor.reserved:
            actor.reserved = False
            self._release_reservation(actor.resources, node, actor.pg)

    # placement groups --------------------------------------------------------

    async def _h_create_placement_group(self, conn, msg):
        pg_id = msg["pg_id"]
        bundles = [Bundle(resources=dict(b), available=dict(b)) for b in msg["bundles"]]
        pg = PGInfo(pg_id=pg_id, bundles=bundles, strategy=msg["strategy"], name=msg.get("name"))
        self.pgs[pg_id] = pg
        if pg.name:
            self.named_pgs[pg.name] = pg_id
        self._emit_event(
            "INFO", "PG_CREATED",
            f"placement group {pg.name or pg_id[:8]} requested "
            f"({len(pg.bundles)} bundles, {pg.strategy})",
            data={"placement_group_id": pg_id, "strategy": pg.strategy,
                  "bundles": len(pg.bundles)})
        self._try_reserve_pg(pg)
        self._wake_scheduler()
        return {"ok": True}

    async def _h_pg_wait(self, conn, msg):
        pg = self.pgs[msg["pg_id"]]
        timeout = msg.get("timeout")
        if timeout is None:
            await pg.ready_event.wait()
        else:
            try:
                await asyncio.wait_for(pg.ready_event.wait(), timeout)
            except asyncio.TimeoutError:
                raise GetTimeoutError("placement group not ready") from None
        return {"state": pg.state, "bundle_nodes": [b.node_id for b in pg.bundles]}

    async def _h_remove_placement_group(self, conn, msg):
        pg = self.pgs.get(msg["pg_id"])
        if pg is None or pg.state == "removed":
            return {"ok": True}
        for b in pg.bundles:
            node = self.nodes.get(b.node_id or "")
            if node is not None:
                _res_add(node.available, b.resources)
        pg.state = "removed"
        if pg.name:
            self.named_pgs.pop(pg.name, None)
        self._emit_event(
            "INFO", "PG_REMOVED",
            f"placement group {pg.name or pg.pg_id[:8]} removed",
            data={"placement_group_id": pg.pg_id})
        self._wake_scheduler()
        return {"ok": True}

    def _try_reserve_pg(self, pg: PGInfo) -> None:
        """All-or-nothing bundle reservation (2-phase in the reference,
        gcs_placement_group_scheduler.h:274; atomic here since state is local)."""
        if pg.state != "pending":
            return
        nodes = [n for n in self.nodes.values()
                 if self._schedulable(n)]
        nodes.sort(key=lambda n: n.index)
        trial = {n.node_id: dict(n.available) for n in nodes}
        assignment: List[str] = []
        strategy = pg.strategy
        used_nodes: Set[str] = set()
        for b in pg.bundles:
            placed = None
            candidates = nodes
            if strategy == "STRICT_PACK" and assignment:
                candidates = [n for n in nodes if n.node_id == assignment[0]]
            elif strategy == "STRICT_SPREAD":
                candidates = [n for n in nodes if n.node_id not in used_nodes]
            elif strategy == "PACK" and assignment:
                candidates = sorted(nodes, key=lambda n: (n.node_id != assignment[-1], n.index))
            elif strategy == "SPREAD":
                candidates = sorted(nodes, key=lambda n: (n.node_id in used_nodes, n.index))
            for n in candidates:
                if _res_fits(trial[n.node_id], b.resources):
                    placed = n.node_id
                    break
            if placed is None:
                return  # cannot satisfy yet; retried on resource release
            _res_sub(trial[placed], b.resources)
            assignment.append(placed)
            used_nodes.add(placed)
        # Commit.
        for b, nid in zip(pg.bundles, assignment):
            b.node_id = nid
            b.available = dict(b.resources)
            _res_sub(self.nodes[nid].available, b.resources)
        pg.state = "ready"
        pg.ready_event.set()
        self._emit_event(
            "INFO", "PG_READY",
            f"placement group {pg.name or pg.pg_id[:8]} reserved on "
            f"{len(set(assignment))} node(s)",
            data={"placement_group_id": pg.pg_id,
                  "bundle_nodes": assignment})

    # kv / pubsub / introspection ---------------------------------------------

    async def _h_kv_put(self, conn, msg):
        key = (msg.get("ns", ""), msg["key"])
        exists = key in self.kv
        if msg.get("overwrite", True) or not exists:
            self.kv[key] = msg["value"]
            self._state_dirty = True
            return {"added": not exists}
        return {"added": False}

    async def _h_kv_get(self, conn, msg):
        return self.kv.get((msg.get("ns", ""), msg["key"]))

    async def _h_kv_del(self, conn, msg):
        deleted = self.kv.pop((msg.get("ns", ""), msg["key"]), None) is not None
        if deleted:
            self._state_dirty = True
        return {"deleted": deleted}

    async def _h_kv_keys(self, conn, msg):
        ns = msg.get("ns", "")
        prefix = msg.get("prefix", "")
        return [k for (n, k) in self.kv if n == ns and k.startswith(prefix)]

    async def _h_profile_workers(self, conn, msg):
        """On-demand cluster profiling (reference: dashboard-triggered
        py-spy stack dumps, dashboard/modules/reporter): push a stack-dump
        request to every live worker, gather replies for up to `timeout`
        seconds, return {worker_id: all-thread stack text}. Workers that
        are busy in native code simply miss the window — partial results
        are returned, never an error."""
        req_id, targets, workers = await self._gather_from_workers(
            "stack_dump", float(msg.get("timeout", 2.0)))
        return {"req_id": req_id, "requested": len(targets),
                "workers": workers}

    async def _gather_from_workers(self, kind: str, timeout: float,
                                   extra: Optional[Dict[str, Any]] = None,
                                   worker_ids: Optional[List[str]] = None):
        """Fan a request to the target workers (default: all live) and
        gather replies (arriving as profile_result messages) until all
        respond or the deadline passes — partial results, never an
        error. ``extra`` fields ride along on the request frame. Returns
        (req_id, target worker-id list, replies) — the target list (not
        just a count) so callers like the object census can name exactly
        which shards never answered (dead/SIGKILLed workers)."""
        req_id = uuid.uuid4().hex[:12]
        self._profiles[req_id] = {}
        targets = []
        pool = (list(self.workers.values()) if worker_ids is None
                else [self.workers[w] for w in worker_ids
                      if w in self.workers])
        for w in pool:
            try:
                await w.conn.send(
                    dict(extra or {}, kind=kind, req_id=req_id))
                targets.append(w.worker_id)
            except Exception:
                pass
        deadline = time.monotonic() + timeout
        while (len(self._profiles[req_id]) < len(targets)
               and time.monotonic() < deadline):
            await asyncio.sleep(0.05)
        return req_id, targets, self._profiles.pop(req_id)

    async def _h_profile_result(self, conn, msg):
        bucket = self._profiles.get(msg["req_id"])
        if bucket is not None:
            bucket[msg["worker_id"]] = msg["text"]
        return {"ok": True}

    async def _h_dag_timeline(self, conn, msg):
        """Gather the channel meter's recent per-stage step spans (recv /
        compute / send / blocked ns per microbatch) from every worker
        hosting resident DAG stages. Same fan-out/partial-result contract
        as the stack dump; feeds state.dag_timeline()'s chrome trace."""
        req_id, targets, replies = await self._gather_from_workers(
            "dag_spans", float(msg.get("timeout", 2.0)),
            extra={"dag": msg.get("dag")})
        spans: List[dict] = []
        for wid, text in replies.items():
            try:
                for s in json.loads(text):
                    s["worker_id"] = str(wid)
                    spans.append(s)
            except Exception:
                pass
        spans.sort(key=lambda s: s.get("end_s", 0.0))
        return {"requested": len(targets), "responded": len(replies),
                "spans": spans}

    def _profile_targets(self, msg) -> Optional[List[str]]:
        """Resolve a profile request's scope to worker ids (None = every
        live worker). Entity ids match on prefix, same as the event
        filters."""
        tid = msg.get("task_id")
        aid = msg.get("actor_id")
        nid = msg.get("node_id")
        wid = msg.get("worker_id")
        if not (tid or aid or nid or wid):
            return None
        out: Set[str] = set()
        if wid:
            out |= {w for w in self.workers if w.startswith(wid)}
        if nid:
            out |= {w.worker_id for w in self.workers.values()
                    if w.node_id.startswith(nid)}
        if aid:
            for a in self.actors.values():
                if a.actor_id.startswith(aid) and a.worker_id:
                    out.add(a.worker_id)
        if tid:
            for w in self.workers.values():
                if w.current_task and w.current_task.startswith(tid):
                    out.add(w.worker_id)
        return sorted(out)

    async def _h_profile(self, conn, msg):
        """Cluster flamegraph profiler (reference: the dashboard's
        py-spy flamegraph button, dashboard/modules/reporter — here a
        pure-Python wall-clock sampler inside our own workers): fan the
        sampling request to the target workers, gather their collapsed
        stacks, merge. Partial results are still a profile; a worker
        stuck in native code just misses the window."""
        if not flags.get("RTPU_PROFILER"):
            return {"error": "profiler disabled (RTPU_PROFILER=0)"}
        duration = min(120.0, max(0.1, float(msg.get("duration", 2.0))))
        hz = float(msg.get("hz") or flags.get("RTPU_PROFILER_HZ"))
        targets = self._profile_targets(msg)
        if targets is not None and not targets:
            return {"error": "no live workers match the requested "
                             "task/actor/node/worker filter"}
        from . import profiler

        _, sent_to, replies = await self._gather_from_workers(
            "profile", duration + 5.0,
            extra={"duration": duration, "hz": hz},
            worker_ids=targets)
        merged = profiler.merge_collapsed(replies)
        return {"requested": len(sent_to), "duration": duration, "hz": hz,
                "stacks": merged["stacks"], "samples": merged["samples"],
                "workers": merged["workers"]}

    # ------------------------------------------------------ telemetry plane

    async def _telemetry_loop(self) -> None:
        """Sample every metric family into the TSDB ring each step and
        run the alert rules over it (core/telemetry.py)."""
        while True:
            await asyncio.sleep(self.tsdb.step_s)
            try:
                now = time.time()
                self.tsdb.sample(now, self._metrics_families())
                if self.alerts is not None:
                    self.alerts.evaluate(now, self.tsdb)
                self.tsdb.maybe_persist(
                    now, self.alerts.snapshot() if self.alerts else None)
            except Exception as e:
                # History must never hurt the control plane.
                sys.stderr.write(f"[controller] telemetry step failed: "
                                 f"{e!r}\n")

    async def _h_query_metrics(self, conn, msg):
        """Metrics history (rtpu top / dashboard sparklines / alert
        tooling): plottable series from the TSDB ring with counter->rate
        and histogram->p50/p99 derivation done server-side."""
        if self.tsdb is None:
            return {"enabled": False, "series": [], "now": time.time(),
                    "step_s": 0.0}
        series = self.tsdb.query(
            name=msg.get("name"), prefix=msg.get("prefix"),
            tags=msg.get("tags"), since=msg.get("since"),
            stat=msg.get("stat"),
            window_s=float(msg.get("window_s", 60.0)),
            limit_series=int(msg.get("limit_series", 64)))
        return {"enabled": True, "series": series, "now": time.time(),
                "step_s": self.tsdb.step_s,
                "retain": self.tsdb.retain}

    async def _h_list_alerts(self, conn, msg):
        """Alert rules + current firing state (rtpu top header, tests)."""
        if self.alerts is None:
            return {"enabled": False, "rules": [], "firing": []}
        return {"enabled": True, "rules": list(self.alerts.rules),
                "firing": self.alerts.firing()}

    async def _h_memory_summary(self, conn, msg):
        """`rtpu memory` backend (reference: `ray memory` reference-table
        dump, _private/state.py memory summary): the object directory
        (id/size/storage/node) joined with each worker's local ownership
        stats, gathered with the same fan-out/partial-result contract as
        profiling — a worker busy in native code misses the window."""
        _, _, owners = await self._gather_from_workers(
            "ref_dump", float(msg.get("timeout", 2.0)))
        limit = int(msg.get("limit", 1000))
        # Largest first BEFORE truncating: the memory-debugging view must
        # never drop the biggest objects to insertion order.
        from .object_store import storage_kind

        ranked = sorted(self.objects.items(),
                        key=lambda kv: -kv[1].size)[:limit]
        objs = [{"object_id": oid, "size": loc.size,
                 "storage": storage_kind(loc), "node_id": loc.node_id}
                for oid, loc in ranked]
        arenas = {nid: n.arena_stats for nid, n in self.nodes.items()
                  if n.arena_stats}
        return {"objects": objs, "num_objects": len(self.objects),
                "total_bytes": sum(l.size for l in self.objects.values()),
                "workers": owners, "arenas": arenas}

    def _local_spill_stats(self) -> Dict[str, int]:
        """Spill usage of the controller's own host (agent-less nodes have
        no heartbeat to ride; same local-sampling contract as cpu/mem)."""
        try:
            from .object_store import spill_stats

            return spill_stats()
        except Exception:
            return {}

    def _local_channel_stats(self) -> Dict[str, int]:
        """Channel-fabric footprint of the controller's own host (same
        local-sampling contract as _local_spill_stats)."""
        try:
            from .object_store import host_channel_stats

            return host_channel_stats()
        except Exception:
            return {}

    async def _h_object_census(self, conn, msg):
        """Cluster object census (`rtpu memory --group-by ...`,
        state.summarize_objects, the dashboard /objects page): the object
        directory (size/tier/node ground truth) joined with every live
        process's ownership shard (owner label, pin/borrow/hold counts,
        optional RTPU_CALLSITE creation sites). Partial-tolerant by
        construction: shards that never answer — SIGKILLed or wedged
        workers — are reported as per-shard error strings while survivors'
        rows still aggregate. The requesting driver ships its OWN shard
        inline in the request (the controller cannot fan out to drivers)."""
        if not flags.get("RTPU_CENSUS"):
            return {"enabled": False, "objects": [], "groups": {},
                    "errors": ["census disabled (RTPU_CENSUS=0)"],
                    "num_objects": 0, "total_bytes": 0}
        timeout = float(msg.get("timeout")
                        or flags.get("RTPU_CENSUS_TIMEOUT_S"))
        _, targets, replies = await self._gather_from_workers(
            "census_dump", timeout)
        shards: List[Dict[str, Any]] = []
        errors: List[str] = []
        for wid in targets:
            shard = replies.get(wid)
            if shard is None:
                errors.append(f"worker {wid[:8]}: no census reply within "
                              f"{timeout:.1f}s (dead or unreachable)")
            elif not isinstance(shard, dict):
                errors.append(f"worker {wid[:8]}: malformed shard "
                              f"({type(shard).__name__})")
            elif shard.get("error"):
                errors.append(f"worker {wid[:8]}: {shard['error']}")
            else:
                shards.append(shard)
        drv = msg.get("shard")
        if isinstance(drv, dict):
            shards.append(drv)
        from .object_store import storage_kind

        now = time.time()
        rows: Dict[str, Dict[str, Any]] = {}
        for oid, loc in self.objects.items():
            rows[oid] = {
                "object_id": oid, "size": int(loc.size or 0),
                "tier": storage_kind(loc), "node_id": loc.node_id or "",
                "owner": "", "local_refs": 0, "borrowers": 0, "holds": 0,
                "pins": 0, "callsite": None,
                "age_s": round(now - self.object_created.get(oid, now), 1)}
        # Broadcast replicas are EXTRA bytes on other hosts: one census row
        # per copy under the "replica" tier, keyed so they never collide
        # with the primary.
        for oid, reps in self.object_replicas.items():
            for nid, rep in reps.items():
                rows[f"{oid}+replica:{nid[:8]}"] = {
                    "object_id": oid, "size": int(rep.size or 0),
                    "tier": "replica", "node_id": nid,
                    "owner": "", "local_refs": 0, "borrowers": 0,
                    "holds": 0, "pins": 0, "callsite": None,
                    "age_s": round(
                        now - self.object_created.get(oid, now), 1)}
        for shard in shards:
            label = str(shard.get("label") or "?")
            for r in shard.get("rows") or ():
                oid = r.get("oid")
                if not oid:
                    continue
                base = rows.get(oid)
                if base is None:
                    # Owned-but-unregistered (inline results, directory
                    # races): the shard row is all we know.
                    base = rows[oid] = {
                        "object_id": oid, "size": 0, "tier": "",
                        "node_id": "", "owner": "", "local_refs": 0,
                        "borrowers": 0, "holds": 0, "pins": 0,
                        "callsite": None, "age_s": 0.0}
                if r.get("owned"):
                    base["owner"] = base["owner"] or label
                    base["local_refs"] = int(r.get("local") or 0)
                    base["borrowers"] = int(r.get("borrowers") or 0)
                    base["holds"] = int(r.get("holds") or 0)
                    base["pins"] = int(r.get("pins") or 0)
                    if r.get("callsite"):
                        base["callsite"] = r["callsite"]
                if not base["size"]:
                    base["size"] = int(r.get("size") or 0)
                if not base["tier"]:
                    base["tier"] = r.get("tier") or ""
        # Owner fallback from the put-path source connection: a census
        # asked for by a DIFFERENT client (the `rtpu memory` CLI, the
        # dashboard) cannot ship the driver's shard, but the directory
        # remembers which connection registered each object — enough to
        # keep driver/worker puts attributed instead of "(unknown)".
        src_label: Dict[int, str] = {}
        for w in self.workers.values():
            if w.conn is not None:
                src_label[id(w.conn)] = f"worker:{w.worker_id[:8]}"
        for dconn in self.driver_conns:
            src_label.setdefault(id(dconn), "driver")
        for r in rows.values():
            if r["owner"]:
                continue
            src = self.object_src.get(r["object_id"])
            if src is not None:
                r["owner"] = src_label.get(id(src), "")
        # Per-tier breakdown inside every grouping: `--group-by owner`
        # still answers "which tier is that owner's 3 GB sitting in?".
        def _agg(key: str) -> Dict[str, Dict[str, Any]]:
            out: Dict[str, Dict[str, Any]] = {}
            for r in rows.values():
                k = r.get(key) or "(unknown)"
                if key == "node_id":
                    k = k[:12] if k != "(unknown)" else k
                g = out.setdefault(k, {"bytes": 0, "count": 0, "tiers": {}})
                g["bytes"] += r["size"]
                g["count"] += 1
                t = r.get("tier") or "(unknown)"
                g["tiers"][t] = g["tiers"].get(t, 0) + r["size"]
            return out

        groups = {"owner": _agg("owner"), "tier": _agg("tier"),
                  "node": _agg("node_id"), "callsite": _agg("callsite")}
        min_size = int(msg.get("min_size") or 0)
        limit = int(msg.get("limit") or 1000)
        detail = sorted((r for r in rows.values() if r["size"] >= min_size),
                        key=lambda r: -r["size"])[:limit]
        arenas = {nid: n.arena_stats for nid, n in self.nodes.items()
                  if n.arena_stats}
        spill = {nid: (n.spill_stats if n.agent_conn is not None
                       else self._local_spill_stats())
                 for nid, n in self.nodes.items() if n.alive}
        total = sum(r["size"] for r in rows.values())
        return {"enabled": True, "objects": detail, "groups": groups,
                "errors": errors, "num_objects": len(rows),
                "total_bytes": total,
                "shards": len(shards), "requested": len(targets) + 1,
                "arenas": arenas, "spill": spill, "t": now}

    # ------------------------------------------------------- leak watchdog

    async def _leak_watchdog_loop(self) -> None:
        """Flag directory objects past RTPU_LEAK_AGE_S whose registering
        connection is gone as OBJECT_LEAK_SUSPECT — once per object (the
        hang watchdog's self-cleaning dedup-set pattern). Only put-path
        objects carry a source connection; everything else is never
        flagged (objects can only be under-reported, never smeared)."""
        poll = float(flags.get("RTPU_LEAK_POLL_S"))
        while True:
            await asyncio.sleep(poll)
            try:
                self._leak_sweep()
            except Exception as e:
                sys.stderr.write(
                    f"[controller] leak sweep failed: {e!r}\n")

    def _leak_sweep(self) -> None:
        age_s = float(flags.get("RTPU_LEAK_AGE_S"))
        now = time.time()
        live = set(self.objects)
        self._leak_reported &= live
        for d in (self.object_created, self.object_src):
            for oid in [o for o in d if o not in live]:
                d.pop(oid, None)
        for oid, src in list(self.object_src.items()):
            if oid in self._leak_reported:
                continue
            created = self.object_created.get(oid)
            if created is None or now - created < age_s:
                continue
            try:
                dead = src is None or src.closed.is_set()
            except Exception:
                dead = True
            if not dead:
                continue
            loc = self.objects.get(oid)
            self._leak_reported.add(oid)
            self.leak_count += 1
            size = int(getattr(loc, "size", 0) or 0)
            self._emit_event(
                "WARNING", "OBJECT_LEAK_SUSPECT",
                f"object {oid[:8]} ({size} bytes) is "
                f"{now - created:.0f}s old and its owning connection is "
                f"closed — suspected leaked ref",
                data={"object_id": oid, "size": size,
                      "age_s": round(now - created, 1)})

    async def _h_subscribe(self, conn, msg):
        self.subs.setdefault(msg["channel"], []).append(conn)
        return {"ok": True}

    async def _h_publish(self, conn, msg):
        """Batched fan-out (reference: src/ray/pubsub/README.md — the
        long-poll publisher coalesces queued messages per subscriber).
        Publishes within one loop iteration append to per-connection
        buffers; ONE flush task per connection drains them as a single
        pubsub_batch frame, so a burst of M messages to S subscribers
        costs S sends instead of M*S."""
        item = {"channel": msg["channel"], "data": msg["data"]}
        for c in list(self.subs.get(msg["channel"], [])):
            buf = self._pubsub_pending.setdefault(id(c), [c, []])
            buf[1].append(item)
            if len(buf[1]) == 1:  # first item: schedule this conn's flush
                asyncio.get_running_loop().create_task(
                    self._flush_pubsub(id(c)))
        return {"ok": True}

    async def _flush_pubsub(self, conn_key: int) -> None:
        buf = self._pubsub_pending.pop(conn_key, None)
        if buf is None:
            return
        c, items = buf
        try:
            if len(items) == 1:
                await c.send({"kind": "pubsub", **items[0]})
            else:
                await c.send({"kind": "pubsub_batch", "items": items})
        except Exception:
            pass

    async def _h_list_state(self, conn, msg):
        """State API backend (reference: python/ray/util/state/api.py:110 —
        list tasks/actors/nodes/workers/objects + task summaries), reading
        the live tables and the bounded task-event history."""
        what = msg["what"]
        limit = int(msg.get("limit", 1000))
        if what == "tasks":
            latest = self._latest_task_events()
            out = [
                {
                    "task_id": tid,
                    "name": ev.get("label"),
                    "state": {"submitted": "PENDING", "running": "RUNNING",
                              "finished": "FINISHED", "failed": "FAILED",
                              "retry": "PENDING", "reconstruct": "PENDING",
                              "actor_restart": "PENDING"}.get(
                                  ev["event"], ev["event"].upper()),
                    "actor_id": ev.get("actor_id"),
                    "worker_id": ev.get("worker_id"),
                    "node_id": ev.get("node_id"),
                    "ts": ev["ts"],
                }
                for tid, ev in latest.items()
            ]
            return out[-limit:]
        if what == "actors":
            return [
                {
                    "actor_id": a.actor_id,
                    "state": a.state.upper(),
                    "name": a.name,
                    "node_id": a.node_id,
                    "worker_id": a.worker_id,
                    "restarts": a.restart_count,
                    # Newest durable checkpoint the controller holds (0 =
                    # none): tests/operators poll this to know a restart
                    # will restore rather than re-run the constructor.
                    "checkpoint_epoch": (a.checkpoint or {}).get("epoch", 0),
                }
                for a in list(self.actors.values())[:limit]
            ]
        if what == "nodes":
            return (await self._h_cluster_state(conn, msg))["nodes"][:limit]
        if what == "workers":
            return [
                {
                    "worker_id": w.worker_id,
                    "node_id": w.node_id,
                    "state": w.state,
                    "current_task": w.current_task,
                    "tpu_capable": w.tpu_capable,
                    "chip_ids": list(w.chip_ids),
                    # Joins the agent heartbeat proc_stats (cpu/rss by pid).
                    "pid": w.pid,
                }
                for w in list(self.workers.values())[:limit]
            ]
        if what == "objects":
            from .object_store import storage_kind

            return [
                {
                    "object_id": oid,
                    "size": loc.size,
                    "backend": storage_kind(loc),
                    "node_id": loc.node_id,
                    "is_error": loc.is_error,
                }
                for oid, loc in list(self.objects.items())[:limit]
            ]
        if what == "placement_groups":
            return [
                {
                    "placement_group_id": pg.pg_id,
                    "name": pg.name,
                    "state": pg.state.upper(),
                    "strategy": pg.strategy,
                    "bundles": [
                        {"bundle_index": i, "resources": dict(b.resources),
                         "node_id": b.node_id}
                        for i, b in enumerate(pg.bundles)
                    ],
                }
                for pg in list(self.pgs.values())[:limit]
            ]
        if what == "dags":
            return [
                dict({
                    "dag_id": d["dag_id"],
                    "stages": [dict(s) for s in d.get("stages", ())],
                    "edges": dict(d.get("edges", {})),
                    "depth": d.get("depth", 0),
                    "since": d.get("since", 0.0),
                    "recoveries": d.get("recoveries", 0),
                    "recovering": d.get("recovering", False),
                    "last_recovery_s": d.get("last_recovery_s"),
                    "last_cause": d.get("last_cause"),
                }, **self._dag_rollup(d))
                for d in list(self.compiled_dags.values())[:limit]
            ]
        if what == "summary":
            counts: Dict[str, Dict[str, int]] = {}
            for ev in self._latest_task_events().values():
                row = counts.setdefault(ev.get("label") or "?", {})
                row[ev["event"]] = row.get(ev["event"], 0) + 1
            return counts
        if what == "summary_breakdown":
            # Per-label per-phase latency percentiles (reference: the
            # `ray summary tasks` timing columns the GcsTaskManager feeds).
            return self._phase_breakdown()
        raise ValueError(f"unknown state listing {what!r}")

    def _dag_rollup(self, d: dict) -> Dict[str, Any]:
        """Channel-meter rollup for one compiled DAG, merged into its
        `list_state("dags")` row: latest per-stage busy fractions and
        per-edge ring stats from the app-metric store (gauges keep last,
        counters accumulate — see _h_metric_update), steps/s from the
        TSDB rate, and THE bottleneck verdict
        (dag.meter.attribute_bottleneck). All fields degrade to empty /
        None when RTPU_DAG_METER=0 or nothing has sampled yet."""
        short = d["dag_id"][:12]
        busy: Dict[str, Dict[str, float]] = {}
        fam = self.app_metrics.get("rtpu_dag_stage_busy_fraction")
        for tags, v in (fam or {}).get("data", {}).items():
            t = dict(tags)
            if t.get("dag") != short:
                continue
            busy.setdefault(t.get("stage", "?"), {})[
                t.get("phase", "?")] = float(v)
        edges: Dict[str, Dict[str, float]] = {}
        for name, field in (
                ("rtpu_dag_edge_items_total", "items"),
                ("rtpu_dag_edge_bytes_total", "bytes"),
                ("rtpu_dag_edge_occupancy", "occupancy"),
                ("rtpu_dag_edge_lag_seqs", "lag"),
                ("rtpu_dag_edge_blocked_fraction", "blocked_fraction")):
            fam = self.app_metrics.get(name)
            for tags, v in (fam or {}).get("data", {}).items():
                t = dict(tags)
                if t.get("dag") != short:
                    continue
                edges.setdefault(t.get("edge", "?"), {})[field] = float(v)
        steps_per_s = None
        if self.tsdb is not None:
            try:
                # The fastest stage's rate IS the pipeline's steady-state
                # throughput floor-to-ceiling band top; during warmup /
                # recovery slower stages would underreport it.
                for ser in self.tsdb.query(
                        name="rtpu_dag_stage_steps_total",
                        tags={"dag": short}):
                    pts = ser.get("points") or ()
                    if pts:
                        steps_per_s = max(steps_per_s or 0.0,
                                          float(pts[-1][1]))
            except Exception:
                pass
        bottleneck = None
        if busy:
            from ray_tpu.dag import meter as dag_meter
            bottleneck = dag_meter.attribute_bottleneck(busy)
        return {"stage_busy": busy, "edge_stats": edges,
                "steps_per_s": steps_per_s, "bottleneck": bottleneck}

    def _latest_task_events(self) -> Dict[str, Dict[str, Any]]:
        """task_id -> its most recent LIFECYCLE event (events append in
        order). Flight-recorder "phases" entries are annotations riding the
        same ring — they must not shadow a task's state."""
        latest: Dict[str, Dict[str, Any]] = {}
        for ev in self.task_events:
            if ev["event"] != "phases":
                latest[ev["task_id"]] = ev
        return latest

    async def _h_autoscaler_state(self, conn, msg):
        """Demand/usage snapshot for the autoscaler (reference: the load
        metrics the monitor feeds StandardAutoscaler,
        autoscaler/_private/load_metrics.py)."""
        demands = []
        for tid in self.pending_queue.ids():
            spec = self.tasks.get(tid)
            if spec is not None:
                demands.append(dict(spec.get("resources", {})))
        # Pending placement-group bundles are demand too (reference:
        # load_metrics pending_placement_groups) — the GCE slice loop
        # scales up on a TPU-{type}-head bundle before any task exists.
        for pg in self.pgs.values():
            if pg.state == "pending":
                for b in pg.bundles:
                    if b.node_id is None:
                        demands.append(dict(b.resources))
        nodes = []
        for n in self.nodes.values():
            busy = False
            for wid in n.workers:
                w = self.workers.get(wid)
                if w is not None and (w.state != "idle" or w.actor_ids):
                    busy = True
                    break
            nodes.append({
                "node_id": n.node_id,
                "alive": n.alive,
                "state": self._node_state(n),
                "is_agent": n.agent_conn is not None,
                "busy": busy,
                "resources": dict(n.resources),
                "available": dict(n.available),
                "labels": dict(n.labels),
            })
        return {"demands": demands, "nodes": nodes}

    # ------------------------------------------------------------- node drain
    # Reference: the DrainNode protocol (autoscaler.proto:334 DrainNode,
    # node_manager.proto:391 DrainRaylet): a node leaves gracefully —
    # scheduling stops, hosted restartable actors migrate (with their state),
    # running tasks get a grace window then re-queue with the preempted
    # flag, sole-copy objects are re-replicated, and only then do the
    # chips/capacity leave the cluster.

    @staticmethod
    def _node_state(node: NodeInfo) -> str:
        if node.drained:
            return "drained"
        if not node.alive:
            return "dead"
        if node.draining:
            return "draining"
        if node.suspect:
            return "suspect"
        return "alive"

    @staticmethod
    def _schedulable(node: NodeInfo) -> bool:
        """May NEW work land on this node? Draining nodes are leaving;
        suspect nodes (heartbeat-silent, possibly partitioned) pause
        placements so a heal rejoins without double-scheduled work."""
        return node.alive and not node.draining and not node.suspect

    async def _h_drain_node(self, conn, msg):
        """Start (or report) a node drain. Idempotent: re-draining a
        draining node returns its current state; deadlines only shrink."""
        nid = msg.get("node_id") or ""
        node = self.nodes.get(nid)
        if node is None:
            # Prefix match so operators can pass the short id `rtpu status`
            # prints.
            matches = [n for n in self.nodes.values()
                       if n.node_id.startswith(nid)] if nid else []
            if len(matches) != 1:
                return {"ok": False, "error": f"unknown node {nid!r}"}
            node = matches[0]
        if node.drained or not node.alive:
            return {"ok": True, "node_id": node.node_id,
                    "state": self._node_state(node)}
        if node.labels.get("head") == "1":
            return {"ok": False, "error": "refusing to drain the head node"}
        reason = msg.get("reason") or "manual"
        deadline_s = msg.get("deadline_s")
        if deadline_s is None:
            deadline_s = flags.get("RTPU_DRAIN_DEADLINE_S")
        deadline = time.time() + max(0.0, float(deadline_s))
        if node.draining:
            node.drain_deadline = min(node.drain_deadline, deadline)
            st = self.pending_drains.get(node.node_id)
            if st is not None and node.drain_deadline < st["deadline"]:
                st["deadline"] = node.drain_deadline
                self._state_dirty = True
            return {"ok": True, "node_id": node.node_id, "state": "draining"}
        node.draining = True
        node.drain_reason = reason
        node.drain_deadline = deadline
        self.drain_counts[reason] = self.drain_counts.get(reason, 0) + 1
        self.pending_drains[node.node_id] = {"reason": reason,
                                             "deadline": deadline}
        self._state_dirty = True
        self._export_event("NODE", {"node_id": node.node_id,
                                    "event": "draining", "reason": reason,
                                    "ts": time.time()})
        self._emit_event(
            "WARNING", "NODE_DRAINING",
            f"node {node.node_id[:8]} draining (reason={reason}, "
            f"deadline in {max(0.0, deadline - time.time()):.1f}s)",
            node_id=node.node_id,
            data={"reason": reason, "deadline": deadline})
        self._arm_drain(node)
        return {"ok": True, "node_id": node.node_id, "state": "draining"}

    def _arm_drain(self, node: NodeInfo) -> None:
        st = self.pending_drains.get(node.node_id)
        if st is None:
            return
        node.draining = True
        node.drain_reason = st.get("reason", "manual")
        node.drain_deadline = float(st.get("deadline", 0.0))
        task = self._drain_tasks.get(node.node_id)
        if task is not None and not task.done():
            return
        self._drain_tasks[node.node_id] = (
            asyncio.get_running_loop().create_task(self._drain_node(node)))

    async def _drain_node(self, node: NodeInfo) -> None:
        try:
            # 1. Proactively migrate restartable/detached actors: their
            # state is snapshotted on the still-healthy worker and restored
            # on the new placement — a planned departure is a move, not a
            # crash-recovery (restart_count untouched).
            for actor in list(self.actors.values()):
                if (actor.node_id == node.node_id
                        and actor.state == "alive"
                        and actor.creation_spec is not None
                        and (actor.detached
                             or actor.restart_count < actor.max_restarts)):
                    await self._migrate_actor(actor, node)
            # 2. Grace window: let running tasks (and direct leases) finish.
            while time.time() < node.drain_deadline:
                if node.node_id not in self.pending_drains:
                    return  # node died mid-drain; death path took over
                if self._node_quiesced(node):
                    break
                await asyncio.sleep(0.1)
            # 3. Re-replicate objects whose only copy lives on the draining
            # host BEFORE the node (and its chips) leave the free pool.
            await self._evacuate_objects(node)
        except Exception as e:  # pragma: no cover — drain must terminate
            sys.stderr.write(f"[controller] drain error on "
                             f"{node.node_id[:8]}: {e!r}\n")
        await self._finish_drain(node)

    def _node_quiesced(self, node: NodeInfo) -> bool:
        for wid in node.workers:
            w = self.workers.get(wid)
            if w is not None and (w.current_task or w.state == "leased"):
                return False
        for lease in self._leases.values():
            if lease["node_id"] == node.node_id:
                return False
        for actor in self.actors.values():
            if actor.node_id == node.node_id and actor.state in (
                    "alive", "pending"):
                return False
        return True

    async def _migrate_actor(self, actor: ActorInfo, node: NodeInfo) -> None:
        """Move one actor off a draining node: snapshot its instance state
        on the hosting worker (best-effort; falls back to a fresh
        constructor run), retire the old instance, and re-queue the
        creation spec — the scheduler places it on a non-draining node.
        Unlike _maybe_restart_actor this consumes NO restart budget and
        fails no buffered calls (in-flight calls complete on the old
        instance before the snapshot closure reaches the mailbox)."""
        spec = actor.creation_spec
        if spec is None:
            return
        actor.state = "restarting"  # new controller-path calls buffer now
        self._export_event("ACTOR", {"actor_id": actor.actor_id,
                                     "event": "migrating",
                                     "node_id": node.node_id,
                                     "ts": time.time()})
        self._emit_event(
            "INFO", "ACTOR_MIGRATING",
            f"actor {actor.name or actor.actor_id[:8]} migrating off "
            f"draining node {node.node_id[:8]}",
            actor_id=actor.actor_id, node_id=node.node_id,
            data={"name": actor.name,
                  "reason": node.drain_reason})
        from .job_manager import SUPERVISOR_PREFIX

        if (actor.name or "").startswith(SUPERVISOR_PREFIX):
            # The supervisor instance migrates, its entrypoint subprocess
            # cannot: the restored instance relaunches, and a planned
            # drain departure bills no attempt budget (PR 4/16 rule).
            self.jobs.note_supervisor_migrating(actor, node)
        w = self.workers.get(actor.worker_id or "")
        blob = None
        if w is not None:
            try:
                res = await w.conn.request(
                    {"kind": "snapshot_actor", "actor_id": actor.actor_id},
                    timeout=10)
                if isinstance(res, dict):
                    blob = res.get("blob")
            except Exception:
                blob = None
            # Retire the old instance so post-snapshot mutations can't be
            # silently lost; a direct call racing this window fails with
            # ActorDiedError (at-most-once actor-call semantics).
            try:
                await w.conn.send({"kind": "drop_actor",
                                   "actor_id": actor.actor_id})
            except Exception:
                pass
            w.actor_ids.discard(actor.actor_id)
            if not w.actor_ids and w.state == "actor":
                w.state = "idle"
        if actor.reserved:
            actor.reserved = False
            self._release_reservation(actor.resources, node, actor.pg)
        actor.worker_id = None
        actor.node_id = None
        if blob is not None:
            spec["state_blob"] = blob
        else:
            spec.pop("state_blob", None)
        spec["state"] = "pending"
        spec.pop("sched_node", None)
        self.tasks[spec["task_id"]] = spec
        self.pending_queue.append(spec)
        self._record_task_event(spec, "actor_migrate")
        if actor.detached:
            self._state_dirty = True
        self._wake_scheduler()

    async def _evacuate_objects(self, node: NodeInfo) -> None:
        """Pull the raw bytes of every object whose only copy lives on the
        draining host and re-home them in the head's spill directory (the
        same byte layout spilling uses, so every read path already
        understands the rewritten location). Objects that cannot be pulled
        fall back to lineage reconstruction in the node-death path."""
        if node.agent_conn is None or not node.host_id \
                or node.host_id == self.host_id:
            return  # bytes live on the head host and survive worker death
        head = next((n for n in self.nodes.values()
                     if n.agent_conn is None and n.alive), None)
        from .object_store import spill_dir

        CHUNK = 4 * 1024 * 1024
        for oid, loc in list(self.objects.items()):
            if (loc.inline is not None or loc.is_error
                    or loc.host_id != node.host_id):
                continue
            # A broadcast replica on a surviving host already re-homes the
            # bytes: promote it instead of pulling them to head spill.
            reps = self.object_replicas.get(oid) or {}
            rep = next((r for nid, r in reps.items()
                        if nid != node.node_id and r.host_id != node.host_id
                        and self._host_alive(r.host_id)), None)
            if rep is not None:
                self.objects[oid] = rep
                continue
            path = os.path.join(spill_dir(), f"{oid[:32]}.bin")
            try:
                with open(path, "wb") as f:
                    off = 0
                    while off < loc.size:
                        n = min(CHUNK, loc.size - off)
                        raw = await node.agent_conn.request(
                            {"kind": "pull_chunk", "loc": loc,
                             "offset": off, "length": n}, timeout=30)
                        if not raw:
                            raise ConnectionError("short pull")
                        f.write(raw)
                        off += len(raw)
            except Exception:
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue  # node-death reconstruction is the fallback
            if self.objects.get(oid) is not loc:
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue  # freed/replaced while pulling
            import dataclasses as _dc

            self.objects[oid] = _dc.replace(
                loc, arena=None, arena_oid=0, shm_name=None,
                spill_path=path, host_id=self.host_id,
                node_id=head.node_id if head else None)

    async def _finish_drain(self, node: NodeInfo) -> None:
        """Terminal step: the grace window closed (or the node quiesced) —
        kill remaining workers, release the node, run the death path. The
        drained flag routes every resulting task/actor failure through the
        preempted (budget-free) retry paths."""
        self._drain_tasks.pop(node.node_id, None)
        if node.node_id not in self.pending_drains:
            return  # death path already cleaned up mid-drain
        node.drained = True
        self.pending_drains.pop(node.node_id, None)
        self._state_dirty = True
        self._export_event("NODE", {"node_id": node.node_id,
                                    "event": "drained",
                                    "reason": node.drain_reason,
                                    "ts": time.time()})
        self._emit_event(
            "INFO", "NODE_DRAINED",
            f"node {node.node_id[:8]} drained "
            f"(reason={node.drain_reason or 'manual'})",
            node_id=node.node_id, data={"reason": node.drain_reason})
        for wid in list(node.workers):
            w = self.workers.get(wid)
            if w is not None:
                # Graceful stop + proc terminate (local spawns); agent
                # spawns are reaped by their agent's shutdown below.
                await self._shutdown_worker(w)
        if node.agent_conn is not None:
            # The agent kills its workers and exits; its connection drop
            # runs _on_node_death, which sees node.drained.
            try:
                await node.agent_conn.send({"kind": "shutdown"})
            except Exception:
                pass
        else:
            await self._on_node_death(node)
        self._wake_scheduler()

    async def _h_drop_node(self, conn, msg):
        """Legacy immediate scale-down of an agent node — now a
        zero-deadline drain, so even the abrupt path migrates actors and
        re-queues work with the preempted flag instead of crashing it."""
        node = self.nodes.get(msg["node_id"])
        if node is None or node.agent_conn is None:
            return {"ok": False}
        return await self._h_drain_node(conn, {
            "node_id": node.node_id, "reason": msg.get("reason") or "manual",
            "deadline_s": 0.0})

    async def _h_task_events(self, conn, msg):
        """Raw event stream for the chrome-trace timeline export
        (reference: GlobalState.chrome_tracing_dump, _private/state.py:434)."""
        return list(self.task_events)

    async def _h_task_phase_events(self, conn, msg):
        """Flight-recorder batch from a worker (reference: TaskEventBuffer
        batches landing in GcsTaskManager): merge phase events into the
        task-event ring (keyed by task_id, consumed by timeline()), fold
        each phase duration into its derived Prometheus histogram, and
        collect shipped tracing spans for get_cluster_spans()."""
        import bisect

        hists: Dict[Tuple[str, str], dict] = {}  # (metric,label) -> state
        for ev in msg.get("events", ()):
            entry = {
                "task_id": ev.get("task_id"),
                "label": ev.get("label"),
                "actor_id": ev.get("actor_id"),
                "event": "phases",
                "ts": ev.get("end_ts"),
                "worker_id": ev.get("worker_id"),
                "node_id": ev.get("node_id"),
                "start_ts": ev.get("start_ts"),
                "outcome": ev.get("outcome"),
                "phases": dict(ev.get("phases") or {}),
            }
            self.task_events.append(entry)
            self._export_event("TASK_PHASES", entry)
            label = entry["label"] or "?"
            for key, mname in PHASE_METRIC_NAMES.items():
                v = entry["phases"].get(key)
                if v is None:
                    continue
                # Resolve each (metric, label) histogram once per shipped
                # batch, not once per observation — a worker's flush lands
                # hundreds of same-label events at once and this handler
                # runs on the controller's hot thread.
                hk = (mname, label)
                hist = hists.get(hk)
                if hist is None:
                    st = self.app_metrics.setdefault(
                        mname, {"type": "histogram",
                                "help": PHASE_METRIC_HELP.get(mname, ""),
                                "boundaries": list(PHASE_BOUNDARIES),
                                "data": {}})
                    h = st["data"].setdefault(
                        (("label", label),),
                        {"buckets": [0] * (len(st["boundaries"]) + 1),
                         "sum": 0.0, "count": 0})
                    hist = hists[hk] = {"bounds": st["boundaries"], "h": h}
                v = float(v)
                h = hist["h"]
                bounds = hist["bounds"]
                h["buckets"][min(bisect.bisect_left(bounds, v),
                                 len(bounds))] += 1
                h["sum"] += v
                h["count"] += 1
        for d in msg.get("spans", ()):
            self.cluster_spans.append(d)
        return {"ok": True}

    def _observe_phase(self, name: str, label: str, value: float) -> None:
        """One observation into a derived phase histogram; stored in
        app_metrics so the /metrics exposition and grafana generation pick
        it up like any user Histogram."""
        import bisect

        st = self.app_metrics.setdefault(
            name, {"type": "histogram",
                   "help": PHASE_METRIC_HELP.get(name, ""),
                   "boundaries": list(PHASE_BOUNDARIES), "data": {}})
        tags = (("label", label),)
        h = st["data"].setdefault(
            tags, {"buckets": [0] * (len(st["boundaries"]) + 1),
                   "sum": 0.0, "count": 0})
        i = min(bisect.bisect_left(st["boundaries"], value),
                len(st["boundaries"]))
        h["buckets"][i] += 1
        h["sum"] += value
        h["count"] += 1

    def _phase_breakdown(self) -> Dict[str, Dict[str, Dict[str, float]]]:
        """label -> phase -> {count, mean, p50, p99} from the derived
        histograms (state.summarize_tasks(breakdown=True) backend)."""
        out: Dict[str, Dict[str, Dict[str, float]]] = {}
        for key, mname in PHASE_METRIC_NAMES.items():
            st = self.app_metrics.get(mname)
            if not st:
                continue
            bounds = st["boundaries"]
            for tags, h in st["data"].items():
                label = dict(tags).get("label", "?")
                if not h.get("count"):
                    continue
                out.setdefault(label, {})[key] = {
                    "count": h["count"],
                    "mean": h["sum"] / h["count"],
                    "p50": _hist_quantile(bounds, h, 0.5),
                    "p99": _hist_quantile(bounds, h, 0.99),
                }
        return out

    async def _h_get_spans(self, conn, msg):
        """Cluster-wide finished tracing spans (util/tracing.py
        get_cluster_spans): spans shipped by worker flight recorders,
        optionally filtered by trace_id."""
        trace_id = msg.get("trace_id")
        spans = list(self.cluster_spans)
        if trace_id:
            spans = [s for s in spans if s.get("trace_id") == trace_id]
        limit = int(msg.get("limit", 10000))
        return spans[-limit:]

    # ------------------------------------------------ serve request ledger

    def _serve_ledger_row(self, request_id: str) -> Dict[str, Any]:
        """Fetch-or-create one ledger row. Rows created by an early span
        (record still in flight on another process) start "inflight"."""
        row = self.serve_ledger.get(request_id)
        if row is None:
            row = self.serve_ledger[request_id] = {
                "request_id": request_id, "trace_id": "",
                "deployment": "", "method": "", "proto": "",
                "status": "inflight", "error": "", "start_ts": None,
                "wall_s": None, "slo_miss": False, "retained": False,
                "spans": [],
            }
            self._serve_ledger_evict()
        return row

    def _serve_ledger_evict(self) -> None:
        """LRU with slow-request auto-capture: oldest UNFLAGGED row goes
        first; retained rows (SLO miss / shed / deadline) are reclaimed
        only once every unflagged row is gone."""
        cap = max(16, int(flags.get("RTPU_SERVE_LEDGER_MAX")))
        while len(self.serve_ledger) > cap:
            victim = None
            for rid, row in self.serve_ledger.items():
                if not row.get("retained"):
                    victim = rid
                    break
            if victim is None:  # every row is retained: evict oldest
                self.serve_ledger.popitem(last=False)
            else:
                self.serve_ledger.pop(victim, None)

    async def _h_serve_request_events(self, conn, msg):
        """Ingest one shipped batch of serve hop spans + ledger records
        (serve/trace.py _Shipper). Spans fold into their request's row
        (bounded per row); serve.stream spans contribute the token stats;
        the record sets the terminal fields and the retention flag."""
        for d in msg.get("spans", ()):
            rid = d.get("request_id")
            if not rid:
                continue
            row = self._serve_ledger_row(rid)
            if not row["trace_id"]:
                row["trace_id"] = d.get("trace_id") or ""
            if len(row["spans"]) < 128:
                row["spans"].append(d)
            if d.get("name") == "serve.stream":
                a = d.get("attributes") or {}
                for k in ("tokens", "ttft_s", "itl_mean_s", "itl_p50_s",
                          "itl_p99_s", "itl_max_s", "abort_cause",
                          "sent"):
                    if a.get(k) not in (None, ""):
                        row[k] = a[k]
        for r in msg.get("records", ()):
            rid = r.get("request_id")
            if not rid:
                continue
            row = self._serve_ledger_row(rid)
            row.update({k: r[k] for k in
                        ("trace_id", "deployment", "method", "proto",
                         "status", "error", "start_ts", "wall_s",
                         "slo_miss") if k in r})
            row["retained"] = bool(
                r.get("slo_miss")
                or r.get("status") in ("shed", "deadline"))
            self.serve_ledger.move_to_end(rid)
        return {"ok": True}

    async def _h_serve_requests(self, conn, msg):
        """Query the request ledger (state.list_serve_requests / `rtpu
        serve requests` / the dashboard page). Filters: ``model``
        (deployment prefix), ``status``, ``min_latency_s``, ``since``
        (start_ts lower bound), ``request_id`` (prefix — includes the
        per-hop spans for the trace waterfall). Newest first."""
        model = msg.get("model")
        status = msg.get("status")
        min_lat = msg.get("min_latency_s")
        since = msg.get("since")
        rid_pfx = msg.get("request_id")
        with_spans = bool(msg.get("with_spans") or rid_pfx)
        limit = int(msg.get("limit", 100))
        out = []
        for row in reversed(self.serve_ledger.values()):
            if model and not (row.get("deployment") or "").startswith(
                    model):
                continue
            if status and row.get("status") != status:
                continue
            if min_lat is not None and (
                    row.get("wall_s") is None
                    or row["wall_s"] < float(min_lat)):
                continue
            if since is not None and (
                    row.get("start_ts") is None
                    or row["start_ts"] < float(since)):
                continue
            if rid_pfx and not row["request_id"].startswith(rid_pfx):
                continue
            r = dict(row)
            if not with_spans:
                r.pop("spans", None)
                r["n_spans"] = len(row.get("spans") or ())
            out.append(r)
            if len(out) >= limit:
                break
        return out

    # --------------------------------------------------- cluster event log
    # Reference: the cluster-event framework (`ray list cluster-events`,
    # gcs_ray_event_converter.h, the dashboard event feed) — lifecycle
    # transitions as structured, filterable, followable records.

    def _emit_event(self, severity: str, kind: str, message: str,
                    **entities) -> None:
        """One controller-side cluster event (no-op when RTPU_EVENTS=0)."""
        if not flags.get("RTPU_EVENTS"):
            return
        try:
            self.events.emit(severity, kind, message, **entities)
        except Exception:
            pass  # the event feed must never hurt the control plane

    async def _h_get_events(self, conn, msg):
        """Filtered (and optionally long-polled) read of the cluster event
        ring: severity is a minimum level, kinds match exactly, entity ids
        match on prefix, `after_seq` is the follow cursor. Returns
        {events, seq} where seq is the cursor for the next follow poll."""
        kinds = msg.get("kinds")
        if isinstance(kinds, str):
            kinds = [kinds]
        sel = dict(
            severity=msg.get("severity"), kinds=kinds,
            task_id=msg.get("task_id"), actor_id=msg.get("actor_id"),
            node_id=msg.get("node_id"), worker_id=msg.get("worker_id"),
            since=msg.get("since"), after_seq=msg.get("after_seq"),
            limit=int(msg.get("limit", 1000)))
        evs = self.events.query(**sel)
        wait_s = float(msg.get("wait_s") or 0)
        if not evs and wait_s > 0:
            await self.events.wait_for_new(wait_s)
            evs = self.events.query(**sel)
        return {"events": evs, "seq": self.events.seq}

    async def _h_cluster_events(self, conn, msg):
        """Batched events shipped by workers/drivers (events._Shipper) and
        host agents (heartbeat-path flush) — merged into the same ring the
        controller's own emit sites feed."""
        if flags.get("RTPU_EVENTS"):
            for ev in msg.get("events", ()):
                if isinstance(ev, dict) and ev.get("kind"):
                    self.events.append(dict(ev))
        return {"ok": True}

    # ------------------------------------------------- hang/straggler watchdog
    # Reference failure mode (LlamaRL): at scale the dominant outage is a
    # SILENTLY hung step — one straggler blocking a collective. The
    # controller already derives per-label exec-latency histograms from the
    # flight recorder (PR 2); this loop closes the loop by using them to
    # DETECT anomalies: any running task older than
    # max(RTPU_HANG_MIN_S, RTPU_HANG_P99_FACTOR x label-p99) is flagged,
    # and the existing stack_dump worker RPC fires automatically so the
    # event carries every thread's stack — a hung collective shows all
    # members blocked at the same frame without anyone ssh'ing anywhere.

    async def _hang_watchdog_loop(self) -> None:
        while True:
            await asyncio.sleep(flags.get("RTPU_HANG_POLL_S"))
            try:
                await self._hang_sweep()
            except Exception as e:  # pragma: no cover — keep watching
                sys.stderr.write(f"[controller] hang watchdog error: "
                                 f"{e!r}\n")

    def _label_exec_p99(self, label: str) -> Tuple[float, int]:
        """(p99 seconds, observation count) of the label's exec-latency
        histogram — the PR 2 flight-recorder rtpu_task_exec_s series."""
        st = self.app_metrics.get(PHASE_METRIC_NAMES["exec_s"])
        if not st:
            return 0.0, 0
        h = st["data"].get((("label", label),))
        if not h or not h.get("count"):
            return 0.0, 0
        return _hist_quantile(st["boundaries"], h, 0.99), int(h["count"])

    def _hang_threshold(self, label: str) -> Tuple[float, bool]:
        """(threshold seconds, has_history): the cutoff a running task of
        this label may age to before it is flagged. With label history the
        task is a STRAGGLER (slow relative to its peers); without any
        completions to compare against it is simply HUNG."""
        floor = float(flags.get("RTPU_HANG_MIN_S"))
        p99, count = self._label_exec_p99(label)
        if count >= 5 and p99 > 0:
            return max(floor, float(flags.get("RTPU_HANG_P99_FACTOR"))
                       * p99), True
        return floor, False

    async def _hang_sweep(self) -> None:
        now = time.time()
        # __dispatch_ts exists exactly while a spec is out on a worker:
        # stamped at dispatch, popped on every re-queue path.
        running = [
            spec for spec in list(self.tasks.values())
            if spec.get("__dispatch_ts")
        ]
        live = {s["task_id"] for s in running}
        # De-dup set self-cleans: ids of finished/retired tasks drop out,
        # so a task that re-queues (retry) can be flagged again.
        self._hang_reported &= live
        for spec in running:
            tid = spec["task_id"]
            if tid in self._hang_reported:
                continue
            age = now - float(spec["__dispatch_ts"])
            label = spec.get("label") or "?"
            threshold, has_history = self._hang_threshold(label)
            if age < threshold:
                continue
            self._hang_reported.add(tid)
            w = self._executing_worker(spec)
            stack = ""
            if w is not None:
                stack = await self._stack_dump_worker(w)
            kind = "TASK_STRAGGLER" if has_history else "TASK_HUNG"
            what = ("actor call (mailbox stalled)"
                    if spec.get("actor_id") else "task")
            self._emit_event(
                "WARNING" if has_history else "ERROR", kind,
                f"{what} {label!r} ({tid[:8]}) has been running "
                f"{age:.1f}s on worker "
                f"{(w.worker_id[:8] if w else '?')} / node "
                f"{(w.node_id[:8] if w else '?')} "
                f"(threshold {threshold:.1f}s"
                + (f", label p99-based" if has_history else "")
                + "); all-thread stacks attached",
                task_id=tid, actor_id=spec.get("actor_id"),
                worker_id=w.worker_id if w else None,
                node_id=w.node_id if w else spec.get("sched_node"),
                data={"age_s": age, "threshold_s": threshold,
                      "label": label, "stack": stack})

    def _executing_worker(self, spec: Dict[str, Any]) -> Optional[WorkerInfo]:
        aid = spec.get("actor_id")
        if aid and not spec.get("is_actor_creation"):
            actor = self.actors.get(aid)
            if actor is not None:
                return self.workers.get(actor.worker_id or "")
            return None
        tid = spec["task_id"]
        for w in self.workers.values():
            if w.current_task == tid or (spec.get("is_actor_creation")
                                         and aid in w.actor_ids):
                return w
        return None

    async def _stack_dump_worker(self, w: WorkerInfo,
                                 timeout: float = 3.0) -> str:
        """Targeted stack_dump on ONE worker (the fan-out variant is
        _h_profile_workers); same partial-result contract — a worker stuck
        in native code misses the window and the event ships without the
        stack rather than never."""
        req_id = uuid.uuid4().hex[:12]
        self._profiles[req_id] = {}
        try:
            await w.conn.send({"kind": "stack_dump", "req_id": req_id})
        except Exception:
            self._profiles.pop(req_id, None)
            return ""
        deadline = time.monotonic() + timeout
        while (not self._profiles.get(req_id)
               and time.monotonic() < deadline):
            await asyncio.sleep(0.05)
        return (self._profiles.pop(req_id, None) or {}).get(w.worker_id, "")

    def _metrics_families(self) -> Dict[str, Dict[str, Any]]:
        """Every exportable metric family, in exposition order:
        {name: {"type", "help", "boundaries", "data": {tags_tuple: v}}}.
        Single source for the Prometheus text endpoint AND the telemetry
        ring (core/telemetry.py samples this each step), so history
        covers exactly what /metrics shows."""
        def fam(name: str, data: Dict) -> Dict[str, Any]:
            mtype, help_ = CORE_METRIC_META[name]
            return {"type": mtype, "help": help_, "boundaries": [],
                    "data": data}

        families: Dict[str, Dict[str, Any]] = {}
        counts: Dict[str, int] = {}
        for ev in self._latest_task_events().values():
            counts[ev["event"]] = counts.get(ev["event"], 0) + 1
        # Gauge, not counter: "tasks currently in state X" over a bounded
        # event window goes down on transitions/eviction, which would
        # break Prometheus rate() on a counter type.
        families["rtpu_tasks"] = fam("rtpu_tasks", {
            (("state", s),): n for s, n in counts.items()})
        families["rtpu_pending_tasks"] = fam(
            "rtpu_pending_tasks", {(): len(self.pending_queue)})
        families["rtpu_workers"] = fam("rtpu_workers",
                                       {(): len(self.workers)})
        families["rtpu_actors"] = fam("rtpu_actors",
                                      {(): len(self.actors)})
        families["rtpu_nodes_alive"] = fam("rtpu_nodes_alive", {
            (): sum(1 for n in self.nodes.values() if n.alive)})
        families["rtpu_objects"] = fam("rtpu_objects",
                                       {(): len(self.objects)})
        node_states: Dict[str, int] = {}
        for n in self.nodes.values():
            st = self._node_state(n)
            node_states[st] = node_states.get(st, 0) + 1
        families["rtpu_nodes"] = fam("rtpu_nodes", {
            (("state", s),): c for s, c in node_states.items()})
        families["rtpu_node_drains_total"] = fam(
            "rtpu_node_drains_total",
            {(("reason", r),): c for r, c in self.drain_counts.items()})
        families["rtpu_uptime_seconds"] = fam(
            "rtpu_uptime_seconds",
            {(): round(time.time() - self.start_time, 1)})
        families["rtpu_objects_spilled_total"] = fam(
            "rtpu_objects_spilled_total", {(): self.spilled_count})
        # Broadcast byte accounting: 'source' is what left the origin
        # host (~one object size per broadcast regardless of fan-out),
        # 'hop' is the sum received across all chain hops.
        families["rtpu_broadcast_bytes_total"] = fam(
            "rtpu_broadcast_bytes_total",
            {(("role", "source"),): self.broadcast_bytes["source"],
             (("role", "hop"),): self.broadcast_bytes["hop"]})
        families["rtpu_object_replicas"] = fam(
            "rtpu_object_replicas",
            {(): sum(len(r) for r in self.object_replicas.values())})
        families["rtpu_actor_checkpoints_total"] = fam(
            "rtpu_actor_checkpoints_total", {(): self.ckpt_stats["count"]})
        families["rtpu_actor_checkpoint_bytes"] = fam(
            "rtpu_actor_checkpoint_bytes", {(): self.ckpt_stats["bytes"]})
        families["rtpu_leases_active"] = fam("rtpu_leases_active",
                                             {(): len(self._leases)})
        families["rtpu_lease_events_total"] = fam(
            "rtpu_lease_events_total",
            {(("event", k),): v for k, v in self.lease_stats.items()})
        if self._arena is not None:
            st = self._arena.stats()
            families["rtpu_arena_used_bytes"] = fam(
                "rtpu_arena_used_bytes", {(): st["used"]})
            families["rtpu_arena_capacity_bytes"] = fam(
                "rtpu_arena_capacity_bytes", {(): st["capacity"]})
        families["rtpu_node_arena_used_bytes"] = fam(
            "rtpu_node_arena_used_bytes",
            {(("node", n.node_id[:12]),): n.arena_stats.get("used", 0)
             for n in self.nodes.values() if n.arena_stats})
        # Node-level host cpu/mem/log-volume (agent heartbeats; the
        # controller samples its own host once per pass for agent-less
        # nodes — same contract as cluster_state).
        local_cpu = local_mem = None
        local_log_bytes: Optional[int] = None
        mem_data: Dict[Tuple, Any] = {}
        cpu_data: Dict[Tuple, Any] = {}
        log_data: Dict[Tuple, Any] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            key = (("node", n.node_id[:12]),)
            if n.agent_conn is not None:
                mem_data[key] = n.mem_fraction
                cpu_data[key] = n.cpu_percent
                log_data[key] = n.log_bytes
            else:
                if local_cpu is None:
                    try:
                        import psutil

                        local_cpu = psutil.cpu_percent(None)
                        local_mem = psutil.virtual_memory().percent / 100.0
                    except Exception:
                        local_cpu = local_mem = -1.0
                mem_data[key] = (n.mem_fraction if local_mem in (None, -1.0)
                                 else local_mem)
                cpu_data[key] = (n.cpu_percent if local_cpu in (None, -1.0)
                                 else local_cpu)
                if local_log_bytes is None:
                    from .worker_logs import log_volume_bytes

                    try:
                        local_log_bytes = log_volume_bytes()
                    except Exception:
                        local_log_bytes = 0
                log_data[key] = local_log_bytes
        families["rtpu_node_mem_fraction"] = fam("rtpu_node_mem_fraction",
                                                 mem_data)
        families["rtpu_node_cpu_percent"] = fam("rtpu_node_cpu_percent",
                                                cpu_data)
        families["rtpu_worker_log_bytes"] = fam("rtpu_worker_log_bytes",
                                                log_data)
        families["rtpu_events_total"] = fam("rtpu_events_total", {
            (("source", src), ("severity", sev)): n
            for (src, sev), n in
            (self.events.counts.items()
             if getattr(self, "events", None) is not None else ())})
        wcpu: Dict[Tuple, Any] = {}
        wrss: Dict[Tuple, Any] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for pid, st in n.proc_stats.items():
                key = (("node", n.node_id[:12]), ("pid", str(pid)))
                wcpu[key] = st.get("cpu_percent", 0.0)
                wrss[key] = st.get("rss", 0.0)
        families["rtpu_worker_cpu_percent"] = fam(
            "rtpu_worker_cpu_percent", wcpu)
        families["rtpu_worker_rss_bytes"] = fam(
            "rtpu_worker_rss_bytes", wrss)
        rpc = protocol.handler_stats()
        families["rtpu_rpc_handled_total"] = fam(
            "rtpu_rpc_handled_total",
            {(("kind", k),): n for k, (n, _) in rpc.items()})
        families["rtpu_rpc_handler_seconds_total"] = fam(
            "rtpu_rpc_handler_seconds_total",
            {(("kind", k),): round(s, 6) for k, (_, s) in rpc.items()})
        # Object-census gauges: directory bytes by (node, tier) plus
        # broadcast replica copies, per-node arena fill fraction (the
        # object_store_mem_high alert input), and per-node spill bytes.
        from .object_store import storage_kind as _sk

        store_data: Dict[Tuple, Any] = {}
        for loc in self.objects.values():
            key = (("node", (loc.node_id or "?")[:12]),
                   ("tier", _sk(loc)))
            store_data[key] = store_data.get(key, 0) + int(loc.size or 0)
        for reps in self.object_replicas.values():
            for nid, rep in reps.items():
                key = (("node", nid[:12]), ("tier", "replica"))
                store_data[key] = (store_data.get(key, 0)
                                   + int(rep.size or 0))
        families["rtpu_object_store_bytes"] = fam(
            "rtpu_object_store_bytes", store_data)
        fill_data: Dict[Tuple, Any] = {}
        spill_data: Dict[Tuple, Any] = {}
        local_spill: Optional[Dict[str, int]] = None
        for n in self.nodes.values():
            if not n.alive:
                continue
            key = (("node", n.node_id[:12]),)
            ast = n.arena_stats
            if n.agent_conn is None and self._arena is not None:
                ast = self._arena.stats()
            cap = float(ast.get("capacity", 0) or 0) if ast else 0.0
            if cap > 0:
                fill_data[key] = round(ast.get("used", 0) / cap, 4)
            if n.agent_conn is not None:
                sp = n.spill_stats
            else:
                if local_spill is None:
                    local_spill = self._local_spill_stats()
                sp = local_spill
            if sp:
                spill_data[key] = sp.get("bytes", 0)
        families["rtpu_object_store_fill_fraction"] = fam(
            "rtpu_object_store_fill_fraction", fill_data)
        families["rtpu_node_spill_bytes"] = fam(
            "rtpu_node_spill_bytes", spill_data)
        families["rtpu_object_leaks_total"] = fam(
            "rtpu_object_leaks_total", {(): self.leak_count})
        # Job plane (core/job_manager.py): table gauge, attempt-cause
        # counter, and terminal-runtime histogram (built by hand — fam()
        # leaves boundaries empty, histograms need theirs).
        families["rtpu_jobs"] = fam("rtpu_jobs",
                                    self.jobs.status_counts())
        families["rtpu_job_attempts_total"] = fam(
            "rtpu_job_attempts_total", self.jobs.attempt_count_data())
        from .job_manager import JOB_RUNTIME_BOUNDARIES

        _jr_type, _jr_help = CORE_METRIC_META["rtpu_job_runtime_s"]
        families["rtpu_job_runtime_s"] = {
            "type": _jr_type, "help": _jr_help,
            "boundaries": list(JOB_RUNTIME_BOUNDARIES),
            "data": self.jobs.runtime_hist_data()}
        # Conditional families appear once they have samples; the
        # always-set keeps its HELP/TYPE headers from day one.
        for name in [n for n, f in families.items()
                     if not f["data"] and n not in _ALWAYS_EXPORT]:
            del families[name]
        # App-defined metrics (util/metrics.py), sorted by name after the
        # core families.
        for name, m in sorted(self.app_metrics.items()):
            families[name] = m
        return families

    def _metrics_text(self) -> str:
        """Prometheus text exposition (reference: _private/metrics_agent.py
        + ray_metrics_export — collapsed to a controller-local scrape),
        rendered generically from _metrics_families()."""
        def esc(v) -> str:
            # Prometheus label-value escaping: one bad value must not
            # corrupt the whole scrape payload.
            return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                    .replace("\n", "\\n"))

        lines: List[str] = []
        for name, m in self._metrics_families().items():
            if m["help"]:
                lines.append(f"# HELP {name} {m['help']}")
            ptype = "histogram" if m["type"] == "histogram" else m["type"]
            lines.append(f"# TYPE {name} {ptype}")
            for tags, v in sorted(m["data"].items()):
                lbl = ",".join(f'{k}="{esc(val)}"' for k, val in tags)
                if m["type"] == "histogram":
                    cum = 0
                    for i, b in enumerate(m["boundaries"]):
                        cum += v["buckets"][i]
                        le = (lbl + "," if lbl else "") + f'le="{b}"'
                        lines.append(f"{name}_bucket{{{le}}} {cum}")
                    le_inf = (lbl + "," if lbl else "") + 'le="+Inf"'
                    lines.append(f"{name}_bucket{{{le_inf}}} {v['count']}")
                    suffix = f"{{{lbl}}}" if lbl else ""
                    lines.append(f"{name}_sum{suffix} {v['sum']}")
                    lines.append(f"{name}_count{suffix} {v['count']}")
                else:
                    suffix = f"{{{lbl}}}" if lbl else ""
                    lines.append(f"{name}{suffix} {v}")
        return "\n".join(lines) + "\n"

    async def _serve_metrics_http(self, reader, writer) -> None:
        """Minimal HTTP/1.0 responder for GET /metrics — no web framework in
        the core control plane."""
        try:
            await asyncio.wait_for(reader.readline(), 5)
            while True:
                line = await asyncio.wait_for(reader.readline(), 5)
                if not line or line in (b"\r\n", b"\n"):
                    break
            body = self._metrics_text().encode()
            writer.write(
                b"HTTP/1.0 200 OK\r\nContent-Type: text/plain; version=0.0.4"
                b"\r\nContent-Length: " + str(len(body)).encode()
                + b"\r\n\r\n" + body
            )
            await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _h_cluster_state(self, conn, msg):
        # Local (agent-less) nodes share the controller's host: sample its
        # cpu/mem ONCE per call so `rtpu status` surfaces node-level
        # numbers for them too (agent nodes report via heartbeat).
        local_cpu = local_mem = None
        try:
            import psutil

            local_cpu = psutil.cpu_percent(None)
            local_mem = psutil.virtual_memory().percent / 100.0
        except Exception:
            pass
        return {
            "nodes": [
                {
                    "node_id": n.node_id,
                    "resources": dict(n.resources),
                    "available": dict(n.available),
                    "labels": dict(n.labels),
                    "alive": n.alive,
                    # Drain lifecycle: alive | draining | drained | dead
                    # (rtpu status / dashboard node table / serve routing).
                    "state": self._node_state(n),
                    "drain_reason": n.drain_reason,
                    "index": n.index,
                    "num_workers": len(n.workers),
                    "mem_fraction": (
                        n.mem_fraction if n.agent_conn is not None
                        else (local_mem if local_mem is not None
                              else n.mem_fraction)),
                    # Host CPU% (heartbeats for agent nodes, sampled here
                    # for local ones) — the `rtpu status` CPU column.
                    "cpu_percent": (
                        n.cpu_percent if n.agent_conn is not None
                        else (local_cpu if local_cpu is not None
                              else n.cpu_percent)),
                    # Unallocated chip ids (local-spawn nodes): chaos tests
                    # assert free-pool/granted disjointness across restarts.
                    "tpu_free": list(n.tpu_free),
                    # Per-worker-process cpu%/rss (agent heartbeats;
                    # dashboard reporter parity). Empty for virtual nodes.
                    "proc_stats": dict(n.proc_stats),
                    # Object-store occupancy (`rtpu status` STORE/SPILL
                    # columns): arena used/capacity + host spill usage
                    # (heartbeats for agent nodes, sampled locally here).
                    "arena": (dict(n.arena_stats)
                              if n.agent_conn is not None
                              else (self._arena.stats()
                                    if self._arena is not None else {})),
                    "spill": (dict(n.spill_stats)
                              if n.agent_conn is not None
                              else self._local_spill_stats()),
                    # Channel-fabric footprint (live rtpu_ch_* rings).
                    "channels": (dict(n.channel_stats)
                                 if n.agent_conn is not None
                                 else self._local_channel_stats()),
                }
                for n in self.nodes.values()
            ],
            "num_workers": len(self.workers),
            "actors": {
                aid: {"state": a.state, "name": a.name, "node_id": a.node_id}
                for aid, a in self.actors.items()
            },
            "pending_tasks": len(self.pending_queue),
            "uptime_s": time.time() - self.start_time,
            "metrics_port": getattr(self, "metrics_port", 0),
            "compiled_dags": {
                did: {"stages": len(d.get("stages", ())),
                      "edges": d.get("edges", {}),
                      "depth": d.get("depth", 0),
                      "since": d.get("since", 0.0),
                      "recoveries": d.get("recoveries", 0),
                      "recovering": d.get("recovering", False)}
                for did, d in self.compiled_dags.items()
            },
        }

    async def _h_add_node(self, conn, msg):
        nid = self.add_node(msg["resources"], msg.get("labels"))
        return {"node_id": nid}

    async def _h_ping(self, conn, msg):
        return {"pong": True, "t": time.time()}

    # host agents -------------------------------------------------------------

    async def _h_register_node(self, conn, msg):
        """A host agent joins — or, after a controller/agent bounce,
        re-joins — the cluster (reference: raylet node registration with
        the GCS, gcs_node_manager.h; re-registration on NotifyGCSRestart,
        node_manager.proto:373)."""
        nid = msg["node_id"]
        node = self.nodes.get(nid)
        if node is not None:
            # Re-registration under the same identity: refresh the control
            # connection and capacity in place. The agent's surviving
            # workers re-register themselves right after and re-claim their
            # node slots; spawn counters reset (in-flight spawn bookkeeping
            # did not survive the bounce — the agent's reap loop reports
            # any orphaned spawn exits).
            node.agent_conn = conn
            node.agent_addr = tuple(msg["agent_addr"])
            node.host_id = msg.get("host_id") or node.host_id
            node.resources = dict(msg["resources"])
            node.available = dict(msg["resources"])
            node.labels = msg.get("labels") or node.labels
            node.alive = True
            node.suspect = False  # a re-register IS a heartbeat
            node.suspect_since = 0.0
            node.last_heartbeat = time.monotonic()
            node.spawning = 0
            node.spawning_tpu = 0
            node.spawning_envs.clear()
            for a in self.actors.values():
                if a.reserved and a.node_id == nid and a.pg is None:
                    _res_sub(node.available, a.resources)
            self._emit_event(
                "INFO", "NODE_RECONNECTED",
                f"node {nid[:8]} re-registered after a bounce",
                node_id=nid, data={"host_id": node.host_id})
            await self._flush_suspect_calls(node)
            if nid in self.pending_drains:
                # The drain outlived a controller bounce: the re-registered
                # node resumes draining with its original deadline.
                self._arm_drain(node)
        else:
            self._node_counter += 1
            self.nodes[nid] = NodeInfo(
                node_id=nid,
                resources=dict(msg["resources"]),
                available=dict(msg["resources"]),
                index=self._node_counter,
                labels=msg.get("labels") or {},
                agent_conn=conn,
                agent_addr=tuple(msg["agent_addr"]),
                host_id=msg.get("host_id"),
                last_heartbeat=time.monotonic(),
            )
            self._emit_event(
                "INFO", "NODE_ADDED",
                f"node {nid[:8]} joined with {msg['resources']} "
                f"(host agent)",
                node_id=nid,
                data={"resources": dict(msg["resources"]),
                      "host_id": msg.get("host_id")})
        self._wake_scheduler()
        return {"ok": True, "controller_host_id": self.host_id}

    async def _h_heartbeat(self, conn, msg):
        node = self.nodes.get(msg["node_id"])
        if node is not None:
            node.last_heartbeat = time.monotonic()
            if node.suspect and node.alive:
                # The partition/stall healed before the death deadline:
                # un-suspect, resume scheduling, flush buffered actor
                # calls — no actor churn, no double-allocation.
                node.suspect = False
                node.suspect_since = 0.0
                self._emit_event(
                    "INFO", "NODE_HEALED",
                    f"node {node.node_id[:8]} heartbeating again after "
                    f"suspect phase; scheduling resumed",
                    node_id=node.node_id)
                await self._flush_suspect_calls(node)
                self._wake_scheduler()
            node.arena_stats = msg.get("arena") or {}
            node.spill_stats = msg.get("spill") or {}
            node.channel_stats = msg.get("channels") or {}
            if msg.get("mem_fraction") is not None:
                node.mem_fraction = float(msg["mem_fraction"])
            if msg.get("cpu_percent") is not None:
                node.cpu_percent = float(msg["cpu_percent"])
            if msg.get("proc_stats") is not None:
                node.proc_stats = msg["proc_stats"]
            if msg.get("log_bytes") is not None:
                node.log_bytes = int(msg["log_bytes"])
        return None

    async def _h_spawn_exited(self, conn, msg):
        """Agent reports a spawned worker process exited. If it never
        registered, unwind the spawning counters (local spawns use
        _watch_spawn for the same purpose). Registered workers are cleaned
        up via their own conn drop — their token is no longer outstanding,
        so this must not decrement some other pending spawn's count."""
        token = msg["spawn_token"]
        node_id = self._agent_spawns.pop(token, None)
        node = self.nodes.get(node_id or "")
        if node is not None:
            node.spawning = max(0, node.spawning - 1)
            if token in self._tpu_spawn_tokens:
                node.spawning_tpu = max(0, node.spawning_tpu - 1)
        self._release_env_spawn(node, token)
        self._tpu_spawn_tokens.discard(token)
        if msg.get("env_failed"):
            # The agent could not materialize the runtime env: fail the
            # queued tasks rather than retrying the broken install forever.
            self._emit_event(
                "ERROR", "RUNTIME_ENV_FAILED",
                f"runtime env build failed on node "
                f"{(node_id or '?')[:8]}: "
                f"{msg.get('env_error') or 'setup failed'}",
                node_id=node_id,
                data={"env_hash": msg["env_failed"],
                      "error": msg.get("env_error")})
            self._fail_env_tasks(
                msg["env_failed"],
                RuntimeError(msg.get("env_error") or "runtime env setup failed"),
            )
        self._wake_scheduler()
        return None

    async def _h_get_node_agent(self, conn, msg):
        """Resolve the pull-serving address for a node: its agent, or this
        controller for in-controller (head/virtual) nodes."""
        node = self.nodes.get(msg.get("node_id") or "")
        if node is not None and node.agent_addr is not None:
            return {"host": node.agent_addr[0], "port": node.agent_addr[1]}
        return {"host": self.host, "port": self.port}

    async def _h_pull_chunk(self, conn, msg):
        """Serve object bytes for head-host locations (the controller is the
        head node's agent)."""
        from .transfer import read_location_range

        return read_location_range(msg["loc"], msg["offset"], msg["length"])

    async def _h_pull_stream(self, conn, msg):
        """Streamed pull of head-host object bytes: chunks ship back-to-back
        under the consumer's credit window (transfer.py protocol)."""
        from . import transfer

        return await transfer.handle_pull_server_message(conn, msg)

    async def _h_pull_credit(self, conn, msg):
        from . import transfer

        return await transfer.handle_pull_server_message(conn, msg)

    # ------------------------------------------------- broadcast / replicas
    # One-hop broadcast (reference: ray.experimental.channel's bounded
    # broadcast + the pull manager's location fan-out): the source streams
    # each byte once down a pipelined chain of hosts; every hop stores a
    # full local replica and reports it here, so later consumer-local
    # get_locations never cross the network again.

    def _head_node_id(self) -> str:
        for n in self.nodes.values():
            if n.agent_conn is None and n.alive:
                return n.node_id
        return "head"

    def _node_host(self, node: "NodeInfo") -> Optional[str]:
        """A node's host identity; agent-less (head/virtual) nodes live in
        the controller's process and share its host."""
        return node.host_id or self.host_id

    async def _replicate_report(self, payload):
        await self._h_replica_added(None, payload)

    async def _h_replicate_begin(self, conn, msg):
        from . import transfer

        return await transfer.handle_replicate_message(
            conn, msg, node_id=self._head_node_id(),
            report=self._replicate_report)

    async def _h_replicate_chunk(self, conn, msg):
        from . import transfer

        return await transfer.handle_replicate_message(
            conn, msg, node_id=self._head_node_id(),
            report=self._replicate_report)

    async def _h_replicate_end(self, conn, msg):
        from . import transfer

        return await transfer.handle_replicate_message(
            conn, msg, node_id=self._head_node_id(),
            report=self._replicate_report)

    async def _h_replica_added(self, conn, msg):
        """A chain hop sealed its local copy: record the replica location
        and resolve the owning broadcast's pending set."""
        oid = msg["object_id"]
        loc: ObjectLocation = msg["loc"]
        node_id = msg["node_id"]
        if oid in self.objects:
            self.object_replicas.setdefault(oid, {})[node_id] = loc
        else:
            # Object freed while the chain was in flight: release the
            # hop's freshly sealed storage instead of leaking it.
            await self._free_one_location(loc)
        self.broadcast_bytes["hop"] += int(msg.get("bytes_in") or 0)
        st = self._broadcasts.get(msg.get("bid") or "")
        if st is not None:
            st["done"][node_id] = "ok"
            st["pending"].discard(node_id)
            st["event"].set()
        return {"ok": True}

    async def _h_replicate_push_done(self, conn, msg):
        """Source-side completion report: bytes the source actually shipped
        (each byte once, independent of chain length)."""
        self.broadcast_bytes["source"] += int(msg.get("bytes") or 0)
        st = self._broadcasts.get(msg.get("bid") or "")
        if st is not None:
            st["stats"]["source_bytes"] += int(msg.get("bytes") or 0)
            if msg.get("error"):
                st["stats"].setdefault("errors", []).append(msg["error"])
            st["pushes"] -= 1
            st["event"].set()
        return None

    def _broadcast_targets(self, loc: ObjectLocation,
                           node_ids: Optional[List[str]],
                           reps: Dict[str, ObjectLocation]):
        """Resolve + filter broadcast targets: alive, not draining, with a
        reachable sink, one per host, skipping hosts that already hold the
        bytes. Returns ([NodeInfo...], {node_id: skip_reason})."""
        if node_ids:
            nodes = []
            skipped: Dict[str, str] = {}
            for nid in node_ids:
                node = self.nodes.get(nid) or next(
                    (n for k, n in self.nodes.items() if k.startswith(nid)),
                    None)
                if node is None:
                    skipped[nid] = "unknown node"
                else:
                    nodes.append(node)
        else:
            nodes, skipped = list(self.nodes.values()), {}
        have = {loc.host_id} | {r.host_id for r in reps.values()}
        out, seen_hosts = [], set()
        for node in nodes:
            host = self._node_host(node)
            if not node.alive or node.drained:
                skipped[node.node_id] = "node not alive"
            elif node.node_id in self.pending_drains:
                skipped[node.node_id] = "node draining"
            elif node.suspect:
                skipped[node.node_id] = "node suspect"
            elif host in have or node.node_id in reps:
                skipped[node.node_id] = "already local"
            elif host in seen_hosts:
                skipped[node.node_id] = "host already targeted"
            elif node.agent_conn is not None and node.agent_addr is None:
                skipped[node.node_id] = "no sink address"
            else:
                seen_hosts.add(host)
                out.append(node)
        return out, skipped

    def _broadcast_sink(self, node: "NodeInfo") -> Dict[str, Any]:
        if node.agent_addr is not None:
            return {"node_id": node.node_id, "host": node.agent_addr[0],
                    "port": node.agent_addr[1]}
        return {"node_id": node.node_id, "host": self.host,
                "port": self.port}

    async def _launch_broadcast_chain(self, bid: str, loc: ObjectLocation,
                                      chain: List[Dict[str, Any]],
                                      st: Dict[str, Any]) -> bool:
        """Start one chain round from wherever the bytes live: the
        controller itself for head-host sources, else the source host's
        agent (replicate_push)."""
        from . import transfer

        if loc.host_id == self.host_id:
            st["pushes"] += 1

            async def _push():
                try:
                    sent = await transfer.push_replicate_chain(loc, chain, bid)
                    st["stats"]["source_bytes"] += sent
                    self.broadcast_bytes["source"] += sent
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — retried by the round loop
                    st["stats"].setdefault("errors", []).append(repr(e)[:300])
                st["pushes"] -= 1
                st["event"].set()

            task = asyncio.get_running_loop().create_task(_push())
            tasks = getattr(self, "_bcast_push_tasks", None)
            if tasks is None:
                tasks = self._bcast_push_tasks = set()
            tasks.add(task)
            task.add_done_callback(tasks.discard)
            return True
        src_node = next(
            (n for n in self.nodes.values()
             if n.alive and n.agent_conn is not None
             and n.host_id == loc.host_id), None)
        if src_node is None:
            return False
        try:
            await src_node.agent_conn.request(
                {"kind": "replicate_push", "bid": bid, "loc": loc,
                 "chain": chain, "chunk": flags.get("RTPU_PULL_CHUNK"),
                 "window": flags.get("RTPU_PULL_WINDOW")}, timeout=10)
            st["pushes"] += 1
            return True
        except Exception:
            return False

    async def _h_broadcast_object(self, conn, msg):
        """rtpu.broadcast backend: replicate one object's bytes onto N
        hosts over a pipelined chain. Re-routes remaining targets on a
        fresh chain when a hop dies or drains mid-flight; source-side
        bytes stay ~one object size per round regardless of N."""
        oid = msg["object_id"]
        timeout = float(msg.get("timeout") or 120.0)
        deadline = time.monotonic() + timeout
        loc = await self._wait_for_object(oid, deadline)
        if loc.is_error:
            raise ObjectLostError(f"cannot broadcast errored object {oid[:8]}")
        reps = self.object_replicas.setdefault(oid, {})
        if loc.inline is not None:
            # Inline bytes ride the control plane with the location itself:
            # every consumer already gets a local copy.
            return {"ok": True, "inline": True, "replicas": {}, "skipped": {},
                    "stats": {"source_bytes": 0}}
        targets, skipped = self._broadcast_targets(
            loc, msg.get("node_ids"), reps)
        st = {
            "pending": {n.node_id for n in targets},
            "done": {nid: "already local" for nid in skipped
                     if skipped[nid] == "already local"},
            "event": asyncio.Event(),
            "stats": {"source_bytes": 0},
            "pushes": 0,  # launched chains still owing a byte report
        }
        rounds = 0
        bids: List[str] = []
        while st["pending"] and rounds < 3 and time.monotonic() < deadline:
            rounds += 1
            live = []
            for nid in sorted(st["pending"]):
                node = self.nodes.get(nid)
                if node is None or not node.alive or node.drained \
                        or nid in self.pending_drains:
                    st["pending"].discard(nid)
                    st["done"][nid] = "node left during broadcast"
                    continue
                live.append(node)
            if not live:
                break
            bid = ObjectID.generate()[:16]
            # Registered until the RPC returns (not per round): late
            # replica_added / push-done reports must still resolve state.
            self._broadcasts[bid] = st
            bids.append(bid)
            chain = [self._broadcast_sink(n) for n in live]
            src = loc
            if not await self._launch_broadcast_chain(bid, src, chain, st):
                # Source host gone: any sealed replica can re-seed.
                reseed = next((r for r in reps.values()
                               if self._host_alive(r.host_id)), None)
                if reseed is None or not await self._launch_broadcast_chain(
                        bid, reseed, chain, st):
                    break
            round_deadline = min(deadline,
                                 time.monotonic() + max(10.0, timeout / 3))
            while st["pending"] and time.monotonic() < round_deadline:
                st["event"].clear()
                # Nodes that die or drain mid-round are re-routed next round.
                changed = False
                for nid in list(st["pending"]):
                    node = self.nodes.get(nid)
                    if node is None or not node.alive \
                            or nid in self.pending_drains:
                        changed = True
                if changed:
                    break
                try:
                    await asyncio.wait_for(
                        st["event"].wait(),
                        max(0.05, min(0.5, round_deadline - time.monotonic())))
                except asyncio.TimeoutError:
                    pass
        # Let in-flight source pushes report their byte counts before the
        # reply is built (stats.source_bytes is the acceptance signal that
        # each byte left the source once).
        drain_deadline = time.monotonic() + 5.0
        while st["pushes"] > 0 and time.monotonic() < drain_deadline:
            st["event"].clear()
            try:
                await asyncio.wait_for(st["event"].wait(), 0.25)
            except asyncio.TimeoutError:
                pass
        for b in bids:
            self._broadcasts.pop(b, None)
        for nid in st["pending"]:
            st["done"][nid] = "timed out"
        return {
            "ok": not st["pending"],
            "replicas": {nid: v for nid, v in st["done"].items()
                         if v == "ok"},
            "skipped": {**skipped,
                        **{nid: v for nid, v in st["done"].items()
                           if v not in ("ok",)}},
            "stats": st["stats"],
            "rounds": rounds,
        }

    def _host_alive(self, host_id: Optional[str]) -> bool:
        if host_id == self.host_id:
            return True
        return any(n.alive and n.host_id == host_id
                   for n in self.nodes.values())

    def _replica_view(self, oid: str, loc: ObjectLocation,
                      req_node_id: Optional[str]) -> ObjectLocation:
        """Consumer-aware location: hand back the copy local to the
        requester's host when one exists; otherwise attach the replica
        list so the pull can fan across source hosts."""
        reps = self.object_replicas.get(oid)
        if not reps or loc.inline is not None:
            return loc
        req_host = None
        if req_node_id:
            node = self.nodes.get(req_node_id)
            if node is not None:
                req_host = self._node_host(node)
        if req_host:
            if loc.host_id == req_host:
                return loc
            for rep in reps.values():
                if rep.host_id == req_host:
                    return rep
        extra = [r for r in reps.values()
                 if r.host_id != loc.host_id
                 and self._host_alive(r.host_id)]
        if not extra:
            return loc
        import dataclasses as _dc

        return _dc.replace(loc, replicas=extra)

    def _restore_state(self) -> None:
        self._restored_detached: List[Dict[str, Any]] = []
        self._adopt_grace_until = 0.0
        if not self.persist_path or not os.path.exists(self.persist_path):
            return
        import pickle as _p

        try:
            with open(self.persist_path, "rb") as f:
                snap = _p.load(f)
        except Exception as e:
            sys.stderr.write(f"[controller] state restore failed: {e!r}\n")
            return
        self.kv.update(snap.get("kv", {}))
        self.functions.update(snap.get("functions", {}))
        # Job table + attempt counters + runtime histogram: restored
        # before anything can touch them, so an in-flight wait_job's
        # after_seq cursor stays meaningful across the bounce.
        self.jobs.restore(snap.get("jobs"))
        # In-progress drains resume after the bounce (wall-clock deadlines,
        # so the grace window keeps shrinking through the downtime).
        drains = snap.get("drains") or {}
        self.drain_counts.update(drains.get("counts") or {})
        self.pending_drains.update(drains.get("pending") or {})
        # Node table (non-agent nodes only — agents re-register themselves):
        # restored so that surviving workers of the previous controller can
        # reconnect under their original node ids and so the head node keeps
        # its identity across a bounce (reference: the GCS node table in
        # gcs_storage surviving failover).
        for nd in snap.get("nodes", []):
            if nd["node_id"] in self.nodes:
                continue
            self._node_counter += 1
            self.nodes[nd["node_id"]] = NodeInfo(
                node_id=nd["node_id"],
                resources=dict(nd["resources"]),
                available=dict(nd["resources"]),
                index=self._node_counter,
                labels=dict(nd.get("labels") or {}),
                tpu_free=list(range(int(nd["resources"].get("TPU", 0)))),
            )
        # Only resume detached actors that can actually be rebuilt: creation
        # deps died with the old process's object plane, and placement
        # groups are not persisted — resuming those would leave actors
        # permanently pending with callers hanging.
        resumable = []
        for spec in snap.get("detached_actors", []):
            if spec.get("deps") or spec.get("pg"):
                sys.stderr.write(
                    f"[controller] not resuming detached actor "
                    f"{spec.get('name') or spec['actor_id'][:8]}: creation "
                    f"{'deps' if spec.get('deps') else 'placement group'} "
                    f"did not survive the restart\n")
                continue
            resumable.append(spec)
        resumed_ids = {s["actor_id"] for s in resumable}
        # Names must only point at actors that exist (now or imminently);
        # dangling entries would KeyError every lookup forever.
        self.named_actors.update({
            k: v for k, v in snap.get("named_actors", {}).items()
            if v in resumed_ids
        })
        # Register the ActorInfos NOW so get_actor() between start and the
        # first scheduler pass sees a restarting actor, not a missing name
        # (calls submitted meanwhile buffer in pending_calls). Re-CREATION
        # is deferred for an adoption grace window: the previous
        # controller's workers may still be alive and hosting these very
        # instances — they re-claim them on reconnect, preserving actor
        # state (reference: GCS failover waits for raylet/worker
        # re-registration before reconstructing actors).
        for spec in resumable:
            actor_id = spec["actor_id"]
            if actor_id in self.actors:
                continue
            actor = ActorInfo(
                actor_id=actor_id,
                name=spec.get("name"),
                state="restarting",
                resources=spec.get("resources", {}),
                pg=spec.get("pg"),
                detached=True,
                creation_task_id=spec["task_id"],
                max_restarts=int(spec.get("max_restarts", 0)),
                creation_spec=spec,
            )
            # A persisted checkpoint record survives the bounce: the
            # re-created instance restores it instead of re-running the
            # constructor. The 8-byte epoch header keeps the record itself
            # opaque to the controller (user state never unpickles here).
            try:
                import struct as _struct

                with open(f"{self.persist_path}.ckpt.{actor_id}",
                          "rb") as f:
                    raw = f.read()
                (epoch,) = _struct.unpack_from("!Q", raw)
                actor.checkpoint = {"epoch": int(epoch), "blob": raw[8:],
                                    "bytes": len(raw) - 8,
                                    "ts": time.time()}
            except Exception:
                pass
            self.actors[actor_id] = actor
        self._restored_detached = resumable
        if resumable:
            self._adopt_grace_until = (
                time.monotonic() + flags.get("RTPU_RECONNECT_GRACE_S"))

    def _resume_detached_actors(self) -> None:
        """Queue creation tasks for restored detached actors that no
        surviving worker re-claimed within the adoption grace window
        (reference: GCS restart reconstructing actors from storage,
        gcs_actor_manager RestartActor on GCS failover)."""
        specs = getattr(self, "_restored_detached", None) or []
        if not specs:
            return
        if time.monotonic() < self._adopt_grace_until:
            return  # reconnecting workers get first claim
        self._restored_detached = []
        queued = False
        for spec in specs:
            actor_id = spec["actor_id"]
            actor = self.actors.get(actor_id)
            if actor is None or actor.state in ("alive", "dead"):
                continue  # adopted by a reconnected worker (or retired)
            if actor.checkpoint is not None \
                    and actor.checkpoint.get("blob") is not None:
                # Restored persisted checkpoint: the re-creation restores
                # state instead of re-running the constructor.
                spec["state_blob"] = actor.checkpoint["blob"]
            spec["state"] = "pending"
            spec.pop("sched_node", None)
            self.tasks[spec["task_id"]] = spec
            self.pending_queue.append(spec)
            queued = True
        if queued:
            self._wake_scheduler()

    def _snapshot_state(self, force: bool = False) -> None:
        if not self.persist_path:
            return
        if not force and not self._state_dirty:
            return  # nothing changed: skip the pickle + disk write
        self._state_dirty = False
        import pickle as _p

        detached = [
            a.creation_spec for a in self.actors.values()
            if a.detached and a.creation_spec is not None
            and a.state != "dead"
        ]
        live_ids = {s["actor_id"] for s in detached}
        snap = {
            "kv": dict(self.kv),
            "functions": dict(self.functions),
            "named_actors": {
                k: v for k, v in self.named_actors.items() if v in live_ids
            },
            "detached_actors": detached,
            # Non-agent nodes (head + virtual): identity + capacity only.
            # Agent nodes re-register themselves after a restart.
            "nodes": [
                {"node_id": n.node_id, "resources": dict(n.resources),
                 "labels": dict(n.labels)}
                for n in self.nodes.values()
                if n.agent_conn is None and n.agent_addr is None and n.alive
            ],
            "drains": {"counts": dict(self.drain_counts),
                       "pending": dict(self.pending_drains)},
            "jobs": self.jobs.snapshot(),
        }
        tmp = self.persist_path + f".tmp{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                _p.dump(snap, f)
            os.replace(tmp, self.persist_path)
        except Exception as e:
            sys.stderr.write(f"[controller] state snapshot failed: {e!r}\n")

    async def _memory_monitor_loop(self) -> None:
        """Kill a worker when a host crosses the memory threshold
        (reference: src/ray/common/memory_monitor.h:52 + the retriable-FIFO
        worker killing policy, raylet/worker_killing_policy_retriable_fifo.h:
        prefer the NEWEST retriable task — it has made the least progress
        and will be retried — then the newest task of any kind; actors are
        killed last since their state is not reconstructible). ONE victim
        per tick, then resample: freed memory must be observed before the
        next kill, or a single spike over-kills the whole pool."""
        while True:
            # Read per-iteration: operators tune these live (and tests
            # lift the pressure mid-run to let a retried victim finish).
            period = flags.get("RTPU_MEMORY_MONITOR_S")
            threshold = flags.get("RTPU_MEMORY_USAGE_THRESHOLD")
            await asyncio.sleep(period)
            try:
                local_frac = self._local_mem_fraction()
                for node in self.nodes.values():
                    if not node.alive:
                        continue
                    if node.agent_conn is not None:
                        # Agent node: trust its heartbeat only — falling
                        # back to the controller host's own usage would
                        # misattribute local pressure to healthy remote
                        # hosts (agents without psutil report nothing).
                        frac = node.mem_fraction
                    else:
                        frac = local_frac
                    if frac < threshold:
                        continue
                    victim = self._pick_oom_victim(node)
                    if victim is None:
                        continue
                    victim.oom_killed = True
                    sys.stderr.write(
                        f"[controller] memory monitor: host at "
                        f"{frac:.0%} >= {threshold:.0%}, killing worker "
                        f"{victim.worker_id[:8]} "
                        f"(task {victim.current_task or 'idle'})\n")
                    # Best-effort final checkpoint before the kill: an
                    # actor victim's state survives when headroom still
                    # allows the serialize (never when the host is already
                    # past the hard ceiling — a checkpoint allocates).
                    if (victim.actor_ids
                            and flags.get("RTPU_ACTOR_CHECKPOINT")
                            and frac < min(0.99, threshold + 0.03)):
                        for aid in list(victim.actor_ids):
                            actor = self.actors.get(aid)
                            if actor is None:
                                continue
                            try:
                                res = await victim.conn.request(
                                    {"kind": "checkpoint_actor",
                                     "actor_id": aid}, timeout=3)
                            except Exception:
                                continue
                            if isinstance(res, dict) and res.get("blob"):
                                self._store_actor_checkpoint(
                                    actor, res["epoch"], res["blob"])
                    await self._shutdown_worker(victim)
                    if victim.spawn_token is not None:
                        # Agent-spawned: no local proc handle — escalate to
                        # the owning agent's SIGTERM (a busy worker ignores
                        # the graceful shutdown message).
                        if node.agent_conn is not None:
                            try:
                                await node.agent_conn.send(
                                    {"kind": "kill_worker",
                                     "spawn_token": victim.spawn_token})
                            except Exception:
                                pass
                    break  # one victim per tick, then resample
            except Exception as e:  # pragma: no cover — keep monitoring
                sys.stderr.write(f"[controller] memory monitor error: {e!r}\n")

    @staticmethod
    def _local_mem_fraction() -> float:
        try:
            import psutil

            return psutil.virtual_memory().percent / 100.0
        except Exception:
            return 0.0

    def _pick_oom_victim(self, node: NodeInfo) -> Optional[WorkerInfo]:
        running = [
            w for wid in node.workers
            if (w := self.workers.get(wid)) is not None and w.current_task
        ]

        def retriable(w: WorkerInfo) -> bool:
            spec = self.tasks.get(w.current_task or "")
            if spec is None:
                return False
            return (int(spec.get("max_retries", 0))
                    - int(spec.get("_retry_count", 0))) > 0

        pool = [w for w in running if retriable(w)] or running
        if pool:
            return max(pool, key=lambda w: w.task_started)
        # Last resort: an actor worker. Prefer one whose actors ALL have a
        # durable checkpoint — its state survives the kill (restored on
        # restart), while an uncheckpointed actor's state is simply lost;
        # ties break to the newest task as before.
        actors = [
            w for wid in node.workers
            if (w := self.workers.get(wid)) is not None and w.actor_ids
        ]

        def checkpointed(w: WorkerInfo) -> bool:
            return all(
                (a := self.actors.get(aid)) is not None
                and a.checkpoint is not None
                for aid in w.actor_ids)

        return max(actors,
                   key=lambda w: (checkpointed(w), w.task_started),
                   default=None)

    async def _flush_suspect_calls(self, node: NodeInfo) -> None:
        """Dispatch actor calls buffered while the node was suspect."""
        for actor in list(self.actors.values()):
            if actor.node_id != node.node_id or actor.state != "alive":
                continue
            while actor.pending_calls:
                calls, actor.pending_calls = actor.pending_calls, []
                for call in calls:
                    await self._dispatch_actor_call(actor, call)

    async def _health_check_loop(self) -> None:
        """Two-phase failure detector over agent heartbeats (reference:
        gcs_health_check_manager.h:39 periodic checks, with a SWIM-style
        suspect phase in front): silence past RTPU_NODE_TIMEOUT_S marks a
        node SUSPECT — scheduling pauses, actor calls buffer, nothing is
        killed — and only silence past RTPU_DEAD_TIMEOUT_S declares it
        DEAD, so a partition shorter than that heals with no actor churn.
        Also runs the arena memory-pressure check (spill cold objects past
        the high watermark, reference local_object_manager.h:103-122)."""
        while True:
            suspect_after = flags.get("RTPU_NODE_TIMEOUT_S")
            dead_after = max(flags.get("RTPU_DEAD_TIMEOUT_S"), suspect_after)
            await asyncio.sleep(min(2.0, suspect_after / 3))
            now = time.monotonic()
            for node in list(self.nodes.values()):
                if (
                    node.alive
                    and node.agent_conn is not None
                    and node.last_heartbeat
                ):
                    silence = now - node.last_heartbeat
                    if silence > dead_after:
                        self._emit_event(
                            "ERROR", "NODE_DEAD_TIMEOUT",
                            f"node {node.node_id[:8]} silent for "
                            f"{silence:.1f}s (> RTPU_DEAD_TIMEOUT_S); "
                            f"declaring it dead",
                            node_id=node.node_id,
                            data={"silence_s": round(silence, 2)})
                        await self._on_node_death(node)
                    elif silence > suspect_after and not node.suspect:
                        node.suspect = True
                        node.suspect_since = now
                        self._emit_event(
                            "WARNING", "NODE_SUSPECT",
                            f"node {node.node_id[:8]} missed heartbeats "
                            f"for {silence:.1f}s: suspect — scheduling "
                            f"paused until it heals or "
                            f"RTPU_DEAD_TIMEOUT_S passes",
                            node_id=node.node_id,
                            data={"silence_s": round(silence, 2)})
            try:
                await self._maybe_spill_cold_objects()
            except Exception as e:  # pragma: no cover — keep the loop alive
                sys.stderr.write(f"[controller] spill error: {e!r}\n")
            self._resume_detached_actors()
            self._snapshot_state()

    async def _maybe_spill_cold_objects(self) -> None:
        """When the head arena passes the high watermark, move the coldest
        sealed objects to disk until usage drops below the low watermark.
        (Agent arenas spill at put time on their own hosts; proactive remote
        eviction rides the same loc rewrite via the agent's free+spill.)

        The arena copy is NOT deleted immediately: a worker may hold the old
        location for an in-flight read, so deletion defers for a grace
        period and retries while zero-copy pins block it."""
        if self._arena is not None:
            await self._drain_deferred_deletes()
            high = flags.get("RTPU_SPILL_HIGH")
            low = flags.get("RTPU_SPILL_LOW")
            st = self._arena.stats()
            cap = st["capacity"] or 1
            if st["used"] / cap < high:
                return
            my_arena = self._arena.name
            victims = sorted(
                (
                    (self.object_touch.get(oid, 0.0), oid, loc)
                    for oid, loc in self.objects.items()
                    if loc.arena == my_arena and not loc.is_error
                ),
            )
            from .object_store import spill_dir
            from .transfer import read_location_range

            grace = flags.get("RTPU_SPILL_DELETE_GRACE_S")
            spilled_bytes = 0
            need = st["used"] - low * cap
            for _, oid, loc in victims:
                if spilled_bytes >= need:
                    break
                path = os.path.join(spill_dir(), f"{oid[:32]}.bin")

                def write_one(loc=loc, path=path):
                    raw = read_location_range(loc, 0, loc.size)
                    with open(path, "wb") as f:
                        f.write(raw)

                try:
                    # Whole-object read+write off the event loop: a spill
                    # sweep must not stall RPC handling.
                    await asyncio.to_thread(write_one)
                except Exception:
                    continue
                if self.objects.get(oid) is not loc:
                    # Freed (or replaced) while the write was in flight:
                    # the free path already handled the arena copy — don't
                    # resurrect the object or defer a bogus delete.
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue
                import dataclasses as _dc

                new_loc = _dc.replace(loc, arena=None, arena_oid=0,
                                      spill_path=path)
                self.objects[oid] = new_loc
                self._deferred_arena_deletes.append(
                    (time.monotonic() + grace, loc.arena_oid))
                spilled_bytes += loc.size
                self.spilled_count += 1

    async def _drain_deferred_deletes(self) -> None:
        now = time.monotonic()
        keep = []
        for due, arena_oid in self._deferred_arena_deletes:
            if due > now:
                keep.append((due, arena_oid))
                continue
            # delete() refuses while a zero-copy pin holds the object; retry
            # later rather than leaking the slot forever.
            if not self._arena.delete(arena_oid):
                keep.append((now + 5.0, arena_oid))
        self._deferred_arena_deletes = keep

    # ---------------------------------------------------------- object helpers

    def _store_location(self, loc: ObjectLocation) -> None:
        self.objects[loc.object_id] = loc
        # Fresh objects are the HOTTEST, not coldest: without this a
        # just-put batch ties at 0.0 and gets spilled first.
        self.object_touch.setdefault(loc.object_id, time.monotonic())
        # Census age + leak-watchdog clock (setdefault: a spill rewrite or
        # replica promote must not reset an object's age).
        self.object_created.setdefault(loc.object_id, time.time())
        for ev in self.object_waiters.pop(loc.object_id, []):
            ev.set()
        for cb in self.object_callbacks.pop(loc.object_id, []):
            try:
                cb(loc.object_id)
            except Exception:
                pass

    def _store_error(self, object_id: str, err: Exception) -> None:
        import pickle as _p

        data = _p.dumps(err)
        loc = ObjectLocation(object_id=object_id, size=len(data), inline=data, is_error=True)
        self._store_location(loc)

    # -------------------------------------------------------------- scheduler

    def _wake_scheduler(self) -> None:
        self._sched_wakeup.set()

    async def _scheduler_loop(self) -> None:
        """Single scheduling fiber (the reference's ScheduleAndDispatchTasks,
        cluster_task_manager.h:117, without the cross-raylet spillback — all
        state is local to the controller here)."""
        while True:
            if self._sched_stuck and len(self.pending_queue):
                # Unplaceable work is queued and nothing is guaranteed to
                # wake us: a lease_reclaim nudge that reached the holder
                # while its routes still had pushes in flight releases
                # nothing, and the holder only reaps idle leases on its
                # next submit — which never comes if the driver is blocked
                # in get() on the queued task's output. Poll so the next
                # pass re-nudges once the holder's routes drain.
                try:
                    await asyncio.wait_for(self._sched_wakeup.wait(), 0.5)
                except asyncio.TimeoutError:
                    pass
            else:
                await self._sched_wakeup.wait()
            self._sched_wakeup.clear()
            try:
                await self._schedule_once()
            except Exception as e:  # pragma: no cover — keep scheduling alive
                sys.stderr.write(f"[controller] scheduler error: {e!r}\n")

    async def _schedule_once(self) -> None:
        # Retry pending placement groups first (resources may have freed).
        for pg in self.pgs.values():
            self._try_reserve_pg(pg)
        # One group = one placement signature: place from the head until
        # the first failure, then the rest of the group is infeasible for
        # this pass too (identical asks). See _PendingQueue docstring.
        stuck = False
        for sig in list(self.pending_queue.groups):
            q = self.pending_queue.groups.get(sig)
            while q:
                spec = self.tasks.get(q[0])
                if spec is None:
                    q.popleft()
                    self.pending_queue._count -= 1
                    continue
                dl = spec.get("deadline_ts")
                if dl is not None and time.time() > dl:
                    # Expired while queued: dead work never places.
                    q.popleft()
                    self.pending_queue._count -= 1
                    self._fail_task(spec, DeadlineExceededError(
                        f"task {spec['task_id'][:8]} deadline passed while queued"))
                    self._record_task_event(spec, "deadline_exceeded")
                    continue
                placed = await self._try_place(spec)
                if not placed:
                    stuck = True
                    break
                q.popleft()
                self.pending_queue._count -= 1
            if q is not None and not q:
                self.pending_queue.groups.pop(sig, None)
        self._sched_stuck = stuck
        if stuck:
            await self._nudge_lease_reclaim()

    async def _nudge_lease_reclaim(self) -> None:
        """Work is queued but unplaceable while drivers hold task leases:
        ask each holder to give back idle leases (it releases any with no
        in-flight pushes). Holder-coordinated, so no double-booking — the
        reference's lease revocation works the same way via ReturnWorker."""
        leases = self._leases
        if not leases:
            return
        now = time.monotonic()
        if now - self._last_reclaim_nudge < 0.2:
            return
        self._last_reclaim_nudge = now
        owners: Dict[Any, List[str]] = {}
        for lid, lease in leases.items():
            owners.setdefault(lease["owner"], []).append(lid)
        for conn, lids in owners.items():
            self.lease_stats["reclaims"] += len(lids)
            try:
                await conn.send({"kind": "lease_reclaim", "lease_ids": lids})
            except Exception:
                pass

    def _eligible_nodes(self, spec,
                        arg_bytes: Optional[Dict[str, int]] = None
                        ) -> List[NodeInfo]:
        strategy = spec.get("scheduling", {"type": "DEFAULT"})
        # Draining nodes take no new placements (reference: DrainNode makes
        # the raylet unschedulable while its deadline runs down).
        nodes = [n for n in self.nodes.values()
                 if self._schedulable(n)]
        st = strategy.get("type", "DEFAULT")
        # Nodes that spilled this spec back are out for the retry pass
        # (reference: spillback carries the rejecting raylet in the lease
        # request's excluded set) — but ONLY for placement-choice
        # strategies. Hard affinity / label constraints have no alternative
        # node: honoring the exclusion there would strand the task forever,
        # while re-dispatching lets the worker-side spill cap (2) force
        # progress.
        excluded = spec.get("spillback_excluded")
        if excluded and st in ("DEFAULT", "SPREAD"):
            keep = [n for n in nodes if n.node_id not in excluded]
            nodes = keep or nodes  # every node rejected: try them again
        if st == "NODE_AFFINITY":
            hard = [n for n in nodes if n.node_id == strategy["node_id"]]
            if hard or not strategy.get("soft", False):
                return hard
            return sorted(nodes, key=lambda n: n.index)
        if st == "SPREAD":
            # Least-loaded first: spread by available CPU fraction.
            def load(n: NodeInfo) -> float:
                tot = n.resources.get("CPU", 1.0) or 1.0
                return 1.0 - n.available.get("CPU", 0.0) / tot

            return sorted(nodes, key=lambda n: (load(n), n.index))
        if st == "NODE_LABEL":
            want: Dict[str, str] = strategy.get("labels", {})
            return [n for n in nodes if all(n.labels.get(k) == v for k, v in want.items())]
        # DEFAULT: the reference's hybrid policy, with the lease-policy
        # locality term — among equally-cold nodes, prefer the one already
        # holding the most argument bytes (reference: the locality-aware
        # LeasePolicy picks the raylet with the largest located share of
        # the task's args; here the directory is controller-local, so the
        # ranking is one dict walk, no RPCs).
        if arg_bytes is None:
            arg_bytes = self._arg_bytes_by_node(spec)
        return self._hybrid_order(nodes, arg_bytes)

    def _arg_bytes_by_node(self, spec) -> Dict[str, int]:
        """node_id -> bytes of this task's dependencies resident there."""
        by_node: Dict[str, int] = {}
        for oid in spec.get("deps", []) or []:
            loc = self.objects.get(oid)
            if loc is not None and loc.node_id and loc.inline is None:
                by_node[loc.node_id] = by_node.get(loc.node_id, 0) + loc.size
        return by_node

    @staticmethod
    def _cpu_util(n: NodeInfo) -> float:
        """CPU utilization fraction — THE hybrid-policy signal. One
        definition shared by ordering and the spawn-wait gate so they can
        never disagree about a node's bucket."""
        tot = n.resources.get("CPU", 1.0) or 1.0
        return 1.0 - n.available.get("CPU", 0.0) / tot

    @staticmethod
    def _hybrid_order(nodes: List[NodeInfo],
                      arg_bytes: Optional[Dict[str, int]] = None
                      ) -> List[NodeInfo]:
        """Reference hybrid_scheduling_policy.h:29-49: PACK onto nodes
        below the utilization threshold (locality/binpacking) — ordered by
        descending local argument bytes, then index — then SPREAD across
        hot nodes by ascending utilization. RTPU_SCHED_TOP_K > 1
        randomizes among the best k to avoid thundering-herd placement
        when many schedulers race (the reference's top-k term). Shared by
        queue placement AND lease grants so direct dispatch follows the
        same policy."""
        thr = flags.get("RTPU_SCHED_HYBRID_THRESHOLD")
        arg_bytes = arg_bytes or {}

        def hybrid_key(n: NodeInfo):
            util = Controller._cpu_util(n)
            if util < thr:
                return (0, -arg_bytes.get(n.node_id, 0), n.index, 0.0)
            return (1, 0, 0, util)

        ordered = sorted(nodes, key=hybrid_key)
        k = int(flags.get("RTPU_SCHED_TOP_K"))
        if k > 1 and len(ordered) > 1:
            import random

            head = ordered[:k]
            random.shuffle(head)
            ordered = head + ordered[k:]
        return ordered

    async def _try_place(self, spec: Dict[str, Any]) -> bool:
        resources: Dict[str, float] = spec.get("resources", {})
        pg_ref: Optional[Tuple[str, int]] = spec.get("pg")
        if pg_ref is not None:
            pg = self.pgs.get(pg_ref[0])
            if pg is None or pg.state == "removed":
                self._fail_task(spec, ValueError("placement group removed"))
                return True
            if pg.state != "ready":
                return False
            idx = pg_ref[1]
            if idx == -1:
                # "Any bundle" (reference bundle_index=-1): first fitting
                # bundle wins. The spec is rebound only at DISPATCH — a
                # failed attempt must stay -1 so the next pass can pick a
                # different bundle (pinning here would re-create the
                # starve-on-bundle-0 behavior the feature removes).
                idx = next(
                    (i for i, b in enumerate(pg.bundles)
                     if _res_fits(b.available, resources)),
                    None,
                )
                if idx is None:
                    return False
            bundle = pg.bundles[idx]
            node = self.nodes[bundle.node_id]
            if not _res_fits(bundle.available, resources):
                return False
            needs_tpu = resources.get("TPU", 0) > 0
            env_hash = spec.get("env_hash") or ""
            w = self._find_idle_worker(node, needs_tpu, env_hash,
                                       tpu_chips=int(resources.get("TPU", 0)))
            if w is None:
                self._maybe_spawn_worker(node, needs_tpu, spec.get("runtime_env"),
                                         tpu_chips=int(resources.get("TPU", 0)))
                return False
            _res_sub(bundle.available, resources)
            spec["pg"] = (pg_ref[0], idx)  # bind so release credits this bundle
            spec["sched_node"] = node.node_id
            await self._dispatch(spec, node, w)
            return True
        needs_tpu = resources.get("TPU", 0) > 0
        env_hash = spec.get("env_hash") or ""
        # Worker availability must not OVERRIDE the placement policy across
        # utilization buckets: a cold (pack-bucket) node that merely needs a
        # worker spawned beats a hot (spread-bucket) node with a warm
        # worker — the reference commits to the policy's node and starts a
        # worker there. WITHIN a bucket, preferring the node with a warm
        # worker is pure win UNLESS the locality term separates them: a
        # node holding strictly more of this task's argument bytes keeps
        # precedence even while its worker spawns (otherwise the data node
        # loses exactly when it's busy and the bytes cross the network).
        thr = flags.get("RTPU_SCHED_HYBRID_THRESHOLD")
        arg_bytes = self._arg_bytes_by_node(spec)
        # The locality hold only applies where locality ordered the nodes:
        # the DEFAULT hybrid policy. SPREAD deliberately ignores data
        # placement; label/affinity orders have no locality meaning.
        locality_st = spec.get("scheduling",
                               {"type": "DEFAULT"}).get("type") == "DEFAULT"

        def bucket(n: NodeInfo) -> int:
            return 0 if self._cpu_util(n) < thr else 1

        spawning_at: Optional[Tuple[int, int]] = None  # (bucket, arg bytes)
        for node in self._eligible_nodes(spec, arg_bytes):
            if not _res_fits(node.available, resources):
                continue
            if spawning_at is not None:
                sb, sbytes = spawning_at
                if bucket(node) > sb or (
                        locality_st and bucket(node) == sb
                        and arg_bytes.get(node.node_id, 0) < sbytes):
                    return False  # wait for the better node's spawn
            w = self._find_idle_worker(node, needs_tpu, env_hash,
                                       tpu_chips=int(resources.get("TPU", 0)))
            if w is None:
                spawning = self._maybe_spawn_worker(
                    node, needs_tpu, spec.get("runtime_env"),
                    tpu_chips=int(resources.get("TPU", 0)))
                # Hold later (worse) nodes ONLY when a spawn is really
                # coming here; a capped node with nothing in flight must
                # not starve the task off warm workers elsewhere.
                if spawning and spawning_at is None:
                    spawning_at = (bucket(node),
                                   arg_bytes.get(node.node_id, 0))
                continue
            _res_sub(node.available, resources)
            spec["sched_node"] = node.node_id
            await self._dispatch(spec, node, w)
            return True
        return False

    def _find_idle_worker(
        self, node: NodeInfo, needs_tpu: bool = False, env_hash: str = "",
        tpu_chips: int = 0,
    ) -> Optional[WorkerInfo]:
        # Plain work prefers plain workers so the scarce, seconds-to-start
        # TPU-capable workers stay free for TPU tasks. Runtime envs match
        # strictly: an env worker's cwd/sys.path/venv are already mutated.
        # A chip-restricted worker (spawn-time TPU_VISIBLE_CHIPS) only takes
        # tasks its slice can serve: a num_tpus=4 task must not land on a
        # worker that sees one chip (reference: per-lease accelerator-id
        # grants; here the grant is per-worker, so matching does the work).
        fallback: Optional[WorkerInfo] = None
        best: Optional[WorkerInfo] = None
        for wid in node.workers:
            w = self.workers.get(wid)
            if w is None or w.state != "idle" or w.env_hash != env_hash:
                continue
            if needs_tpu:
                if w.tpu_capable and (
                        not w.chip_ids
                        or len(w.chip_ids) >= max(1, tpu_chips)):
                    # Prefer the smallest sufficient restricted worker over
                    # unrestricted ones: an unrestricted process touches
                    # every chip JAX can see, so handing it a small request
                    # while an exact-fit slice idles invites physical
                    # contention with concurrently-running slices.
                    if best is None or (
                            (len(w.chip_ids) or 1 << 30)
                            < (len(best.chip_ids) or 1 << 30)):
                        best = w
            elif w.tpu_capable:
                fallback = fallback or w
            else:
                return w
        return best if needs_tpu else fallback

    def _maybe_spawn_worker(
        self,
        node: NodeInfo,
        needs_tpu: bool = False,
        runtime_env: Optional[Dict[str, Any]] = None,
        tpu_chips: int = 0,
    ) -> bool:
        """True iff a suitable worker spawn is now (or already was) in
        flight on this node — i.e. waiting on this node is sensible.
        False means no spawn will happen (cap reached with no reapable
        victim): callers must NOT hold placement for this node."""
        if node.spawning >= 4:
            return True  # several already coming
        # One in-flight TPU-capable spawn satisfies any number of queued TPU
        # tasks' wakeups during its multi-second startup; without this guard
        # every scheduler pass reaps another idle plain worker and launches a
        # surplus TPU worker. Env spawns (venv builds can take tens of
        # seconds) get the same dedup, keyed by env hash.
        if needs_tpu and node.spawning_tpu > 0:
            return True
        want_env = (runtime_env or {}).get("hash", "")
        if want_env and node.spawning_envs.get(want_env, 0) > 0:
            return True
        if len(node.workers) + node.spawning >= MAX_WORKERS_PER_NODE:
            # At the cap, a task needing a worker flavor (TPU or a runtime
            # env) that no idle worker matches must not starve behind idle
            # mismatched workers: reap one to make room (reference:
            # worker_pool.cc idle worker killing to satisfy the pool cap).
            # Scarce TPU workers are victimized only as a last resort, and
            # only by a TPU-flavored request.
            if not needs_tpu and not want_env:
                # A plain spawn can also ride any in-flight plain spawn.
                return node.spawning > 0
            victim = None
            last_resort = None
            for wid in list(node.workers):
                w = self.workers.get(wid)
                if w is None or w.state != "idle":
                    continue
                if w.tpu_capable:
                    if needs_tpu and w.env_hash != want_env:
                        last_resort = last_resort or w
                    continue
                if not needs_tpu and w.env_hash == want_env:
                    continue  # never reap the flavor being requested
                victim = w
                break
            victim = victim or last_resort
            if victim is None:
                return node.spawning > 0
            node.workers.discard(victim.worker_id)
            self.workers.pop(victim.worker_id, None)
            if victim.chip_ids and node.agent_conn is None:
                # This path pops the worker before shutdown, so the death
                # handler can't return its chips — do it here.
                node.tpu_free.extend(victim.chip_ids)
                victim.chip_ids = []
            asyncio.get_running_loop().create_task(self._shutdown_worker(victim))
        if needs_tpu:
            # Chip-pressure check: spawning a TPU worker whose visibility
            # would overlap chips pinned by LIVE workers trades isolation
            # for "device in use" crashes (libtpu holds devices for process
            # lifetime). If disjoint chips can't be granted, reap an idle
            # chip-holder to replenish the pool and let the scheduler retry
            # after its death; with only busy holders, wait.
            total = int(node.resources.get("TPU", 0))
            k = max(1, tpu_chips)
            if total:
                held = 0
                for wid in node.workers:
                    lw = self.workers.get(wid)
                    if lw is None or not lw.tpu_capable:
                        continue
                    # An unrestricted TPU worker's JAX runtime grabbed every
                    # visible chip — it holds `total`, not zero.
                    held += len(lw.chip_ids) or total
                if total - held < k:
                    dying = None
                    for wid in node.workers:
                        w = self.workers.get(wid)
                        if (w is not None and w.state == "idle"
                                and w.chip_ids):
                            if dying is None or \
                                    len(w.chip_ids) < len(dying.chip_ids):
                                dying = w
                    if dying is not None:
                        dying.state = "dying"  # matcher must skip it now
                        asyncio.get_running_loop().create_task(
                            self._shutdown_worker(dying))
                        return True  # chips free on its death; retry then
                    return node.spawning > 0
        node.spawning += 1
        if needs_tpu:
            node.spawning_tpu += 1
        spawn_token = uuid.uuid4().hex
        if want_env:
            node.spawning_envs[want_env] = (
                node.spawning_envs.get(want_env, 0) + 1)
            self._spawn_env_hash[spawn_token] = want_env
        if node.agent_conn is not None:
            # Delegate to the host agent (lease-style spawn: the reference's
            # raylet owns its worker pool, worker_pool.h:159; the controller
            # only grants the lease).
            self._agent_spawns[spawn_token] = node.node_id
            if needs_tpu:
                self._tpu_spawn_tokens.add(spawn_token)
            sys_path = os.pathsep.join(p or os.getcwd() for p in sys.path)
            asyncio.get_running_loop().create_task(
                node.agent_conn.send(
                    {
                        "kind": "spawn_worker",
                        "spawn_token": spawn_token,
                        "tpu": needs_tpu,
                        "tpu_chips": max(1, tpu_chips) if needs_tpu else 0,
                        "sys_path": sys_path,
                        "runtime_env": runtime_env,
                    }
                )
            )
            return True
        env = flags.child_env()
        env["RTPU_CONTROLLER"] = f"{self.host}:{self.port}"
        env["RTPU_NODE_ID"] = node.node_id
        env["RTPU_SPAWN_TOKEN"] = spawn_token
        if needs_tpu:
            env["RTPU_TPU_WORKER"] = "1"
            self._tpu_spawn_tokens.add(spawn_token)
            # Unit-instance chip assignment (reference: per-instance GPU
            # accounting + CUDA_VISIBLE_DEVICES; tpu.py TPU_VISIBLE_CHIPS):
            # the worker sees only its chips. Freed when the worker dies.
            # If the pool is exhausted (more TPU workers than chips), spawn
            # unrestricted rather than refusing — visibility is an
            # isolation nicety, the hard limit is the float resource.
            k = max(1, tpu_chips)
            if len(node.tpu_free) >= k:
                ids, node.tpu_free = node.tpu_free[:k], node.tpu_free[k:]
                env["TPU_VISIBLE_CHIPS"] = ",".join(map(str, ids))
                self._chip_alloc[spawn_token] = ids
            else:
                # Fewer than k free (idle workers still pin theirs): a
                # partial slice would run a k-chip workload on <k chips —
                # spawn unrestricted instead, per the fallback contract.
                env.pop("TPU_VISIBLE_CHIPS", None)
        else:
            # Plain workers skip the accelerator runtime entirely: the axon
            # PJRT plugin registration in sitecustomize imports jax (~3s of
            # interpreter startup). Control-plane workers must spawn in
            # ~0.3s (reference: prestarted raylet workers, worker_pool.h).
            env.pop("PALLAS_AXON_POOL_IPS", None)
            # An inherited TPU_VISIBLE_CHIPS (chip-restricted driver env)
            # would be reported at registration and freed into tpu_free on
            # death — chips this node never allocated. Strip it.
            env.pop("TPU_VISIBLE_CHIPS", None)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        # Propagate the driver's import path so functions defined in driver-
        # local modules resolve on workers (the lightweight analog of the
        # reference's working_dir runtime env, runtime_env/working_dir.py).
        env["RTPU_SYS_PATH"] = os.pathsep.join(p or os.getcwd() for p in sys.path)
        # Workers never grab the real TPU by default: the mesh layer assigns
        # device visibility explicitly when a training world is formed.
        env.setdefault("JAX_PLATFORMS", "cpu")
        if runtime_env:
            import json as _json

            env["RTPU_RUNTIME_ENV"] = _json.dumps(runtime_env)
        if runtime_env and runtime_env.get("container"):
            # Worker-in-container (reference runtime_env/container.py):
            # wrap the launch in the configured container runtime. A
            # missing runtime binary fails the env's tasks with a clear
            # error instead of a silent uncontained spawn.
            async def _spawn_container():
                from . import runtime_env as renv

                cmd = renv.container_command(
                    runtime_env, [sys.executable, "-m",
                                  "ray_tpu.core.worker_main"])
                try:
                    proc = subprocess.Popen(
                        cmd, env=env,
                        stdout=self._worker_log_file(spawn_token),
                        stderr=subprocess.STDOUT)
                except OSError as e:
                    node.spawning = max(0, node.spawning - 1)
                    self._release_env_spawn(node, spawn_token)
                    self._free_spawn_chips(node, spawn_token)
                    self._fail_env_tasks(
                        runtime_env.get("hash", ""),
                        RuntimeError(
                            f"container runtime {cmd[0]!r} unavailable: "
                            f"{e}"))
                    self._wake_scheduler()
                    return
                self._spawned_procs[spawn_token] = proc
                asyncio.get_running_loop().create_task(
                    self._watch_spawn(node.node_id, spawn_token, proc))

            asyncio.get_running_loop().create_task(_spawn_container())
            return True
        if runtime_env and (runtime_env.get("pip")
                            or runtime_env.get("conda")):
            # venv/conda materialization can take tens of seconds: run it
            # off the event loop, then launch with that env's interpreter.
            async def _spawn_with_venv():
                from . import runtime_env as renv

                try:
                    python = await asyncio.to_thread(
                        renv.spawner_python, runtime_env)
                except Exception as e:
                    sys.stderr.write(
                        f"[controller] runtime env build failed: {e!r}\n")
                    node.spawning = max(0, node.spawning - 1)
                    if spawn_token in self._tpu_spawn_tokens:
                        self._tpu_spawn_tokens.discard(spawn_token)
                        node.spawning_tpu = max(0, node.spawning_tpu - 1)
                    self._release_env_spawn(node, spawn_token)
                    self._free_spawn_chips(node, spawn_token)
                    self._fail_env_tasks(runtime_env.get("hash", ""), e)
                    self._wake_scheduler()
                    return
                proc = subprocess.Popen(
                    [python, "-m", "ray_tpu.core.worker_main"], env=env,
                    stdout=self._worker_log_file(spawn_token),
                    stderr=subprocess.STDOUT)
                self._spawned_procs[spawn_token] = proc
                asyncio.get_running_loop().create_task(
                    self._watch_spawn(node.node_id, spawn_token, proc))

            asyncio.get_running_loop().create_task(_spawn_with_venv())
            return True
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.core.worker_main"],
                env=env,
                stdout=self._worker_log_file(spawn_token),
                stderr=subprocess.STDOUT,
            )
        except OSError:
            # Unwind: a failed launch must not leak the carved-out chips
            # or the spawning counters.
            node.spawning = max(0, node.spawning - 1)
            if spawn_token in self._tpu_spawn_tokens:
                self._tpu_spawn_tokens.discard(spawn_token)
                node.spawning_tpu = max(0, node.spawning_tpu - 1)
            self._release_env_spawn(node, spawn_token)
            self._free_spawn_chips(node, spawn_token)
            return False
        self._spawned_procs[spawn_token] = proc
        # The worker registers itself carrying the token (exact adoption in
        # _h_register); this task only reaps processes that die pre-register.
        asyncio.get_running_loop().create_task(self._watch_spawn(node.node_id, spawn_token, proc))
        return True

    def _free_spawn_chips(self, node: Optional[NodeInfo],
                          spawn_token: str) -> None:
        """Return a never-started/never-registered local spawn's chip grant
        to the node pool."""
        ids = self._chip_alloc.pop(spawn_token, [])
        if ids and node is not None:
            node.tpu_free.extend(ids)

    def _worker_log_file(self, spawn_token: str):
        from .worker_logs import worker_log_file

        return worker_log_file(spawn_token)

    async def _watch_spawn(self, node_id: str, spawn_token: str, proc: subprocess.Popen) -> None:
        # ~2 min of polling: generous for a loaded CI host (TPU workers
        # import jax, ~3-10s; venv workers build first), but bounded so the
        # kill-on-exhaustion below can't hit a healthy slow starter.
        for _ in range(1200):
            await asyncio.sleep(0.1)
            if spawn_token not in self._spawned_procs:
                return  # adopted by a registered worker
            if proc.poll() is not None:
                self._spawned_procs.pop(spawn_token, None)
                node = self.nodes.get(node_id)
                if node:
                    node.spawning = max(0, node.spawning - 1)
                    if spawn_token in self._tpu_spawn_tokens:
                        node.spawning_tpu = max(0, node.spawning_tpu - 1)
                # Died before registering: its chips were never adopted.
                self._free_spawn_chips(node, spawn_token)
                self._release_env_spawn(node, spawn_token)
                self._tpu_spawn_tokens.discard(spawn_token)
                self._wake_scheduler()
                return
        # Watch window exhausted with the process still alive and
        # unregistered: a 60s silent startup is pathological (reference:
        # worker_pool startup timeouts kill slow starters). Kill it and
        # unwind — freeing the chip grant while the process lived on could
        # double-allocate its chips if it registered late.
        if spawn_token in self._spawned_procs:
            try:
                proc.terminate()
            except Exception:
                pass
            for _ in range(20):  # up to 2s for a graceful exit
                await asyncio.sleep(0.1)
                if proc.poll() is not None:
                    break
            else:
                try:
                    proc.kill()
                except Exception:
                    pass
                for _ in range(50):  # SIGKILL is definitive, reap it
                    await asyncio.sleep(0.1)
                    if proc.poll() is not None:
                        break
            self._spawned_procs.pop(spawn_token, None)
            node = self.nodes.get(node_id)
            if node:
                node.spawning = max(0, node.spawning - 1)
                if spawn_token in self._tpu_spawn_tokens:
                    node.spawning_tpu = max(0, node.spawning_tpu - 1)
            if proc.poll() is not None:
                proc.wait()  # reap the zombie
                # Chips return ONLY once the process is truly gone: a
                # still-alive process may hold the devices open, and
                # re-granting its chips double-allocates them.
                self._free_spawn_chips(node, spawn_token)
            else:
                self._chip_alloc.pop(spawn_token, None)
                sys.stderr.write(
                    f"[controller] spawned worker {spawn_token[:8]} "
                    f"survived SIGKILL; leaking its chip grant rather than "
                    f"double-allocating\n")
            self._release_env_spawn(node, spawn_token)
            self._tpu_spawn_tokens.discard(spawn_token)
            self._wake_scheduler()

    async def _dispatch(self, spec: Dict[str, Any], node: NodeInfo, w: WorkerInfo) -> None:
        # Wall-clock dispatch stamp: the hang watchdog ages running work
        # against it (wall clock so it stays meaningful across a bounce).
        spec["__dispatch_ts"] = time.time()
        self._record_task_event(spec, "running", worker_id=w.worker_id,
                                node_id=node.node_id)
        if spec.get("is_actor_creation"):
            actor = self.actors[spec["actor_id"]]
            actor.worker_id = w.worker_id
            actor.node_id = node.node_id
            actor.reserved = True
            # bundle_index=-1 rebinds to the bundle actually used at
            # placement; the actor's release must credit that bundle.
            actor.pg = spec.get("pg", actor.pg)
            w.state = "actor"
            w.actor_ids.add(actor.actor_id)
            await w.conn.send({"kind": "instantiate_actor", "spec": spec})
        else:
            w.state = "task"
            w.current_task = spec["task_id"]
            w.task_started = time.monotonic()
            await w.conn.send({"kind": "execute_task", "spec": spec})

    def _release_task_resources(self, spec: Dict[str, Any]) -> None:
        node = self.nodes.get(spec.get("sched_node", ""))
        if node is None:
            return
        resources = dict(spec.get("resources", {}))
        if spec.get("blocked"):
            resources.pop("CPU", None)  # CPU already released at block time
        self._release_reservation(resources, node, spec.get("pg"))

    def _release_reservation(
        self, resources: Dict[str, float], node: NodeInfo, pg_ref: Optional[Tuple[str, int]]
    ) -> None:
        if pg_ref is not None:
            pg = self.pgs.get(pg_ref[0])
            if pg is not None and pg.state == "ready":
                _res_add(pg.bundles[pg_ref[1]].available, resources)
            # PG removed/pending: the bundle's full reservation was (or will
            # be) returned to the node wholesale at remove time — releasing
            # here too would double-credit the node and oversubscribe it.
            return
        _res_add(node.available, resources)


# ------------------------------------------------------------------ exceptions


class RayTpuError(Exception):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class WorkerCrashedError(RayTpuError):
    pass


class NodePreemptedError(WorkerCrashedError):
    """The hosting node left the cluster on a PLANNED departure — a spot
    preemption notice, a manual `rtpu drain`, or autoscaler idle
    scale-down. Carries ``preempted = True`` so planned departures never
    consume task ``max_retries`` / actor ``max_restarts`` budgets
    (reference: the DrainNode protocol's graceful-departure semantics vs
    unexpected node failure)."""

    preempted = True


class ActorDiedError(RayTpuError):
    pass


class ActorNotHostedError(ActorDiedError):
    """A worker REFUSED an actor call because it no longer hosts the actor
    (it migrated off a draining node, or was killed). The refusal happens
    before any user code runs, so the call PROVABLY never executed —
    callers may safely resubmit it through the controller, which routes to
    the actor's new host (or buffers while it re-creates)."""


class OutOfMemoryError(RayTpuError):
    """A worker was killed by the memory monitor to relieve host memory
    pressure (reference: ray.exceptions.OutOfMemoryError +
    src/ray/common/memory_monitor.h)."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ray_tpu.cancel (reference:
    ray.exceptions.TaskCancelledError)."""


class DeadlineExceededError(RayTpuError, TimeoutError):
    """The request's end-to-end deadline passed before (or while) it ran.
    Raised at every queue boundary — scheduler pop, actor-mailbox dequeue,
    serve router/replica/batcher — so expired work is dropped instead of
    executed (reference: Serve request timeouts + gRPC DEADLINE_EXCEEDED
    semantics)."""


class ObjectLostError(RayTpuError):
    """The bytes of an object died with their host and no lineage could
    reconstruct them (reference: ray.exceptions.ObjectLostError)."""


class RuntimeEnvSetupError(RayTpuError):
    """A task's runtime environment could not be materialized (reference:
    ray.exceptions.RuntimeEnvSetupError)."""


class DependencyError(RayTpuError):
    pass


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task (reference: RayTaskError)."""

    def __init__(self, label: str, cause: Exception, traceback_str: str = ""):
        super().__init__(f"task {label} failed: {cause!r}\n{traceback_str}")
        self.label = label
        self.cause = cause
        self.traceback_str = traceback_str

    def __reduce__(self):
        return (TaskError, (self.label, self.cause, self.traceback_str))
