"""Unique identifiers for the distributed futures core.

Equivalent in role to the reference's ID types (ray: src/ray/common/id.h) but
deliberately simple: 16 random bytes rendered as hex. IDs are value objects used
as dict keys throughout the control plane.
"""
from __future__ import annotations

import os
import binascii
import threading

# Cheap id bytes: os.urandom is a getrandom(2) syscall per call — measured
# ~50us on CI hosts, and id generation (task_id + return oid per submit) was
# the single largest driver-side cost at high submission rates. Ids are
# uniqueness tokens, not secrets (capability tokens elsewhere use
# uuid4/secrets), so a per-thread Mersenne Twister seeded once from
# os.urandom is sufficient: 128 random bits per id keeps collisions
# negligible, at ~1us per id. Per-thread AND per-pid: a forked child
# (multiprocessing spawn paths) reseeds instead of replaying the parent's
# stream, and threads never contend.
_rand_local = threading.local()


def _rand16() -> bytes:
    rng = getattr(_rand_local, "rng", None)
    if rng is None or getattr(_rand_local, "pid", 0) != os.getpid():
        import random

        rng = _rand_local.rng = random.Random(os.urandom(32))
        _rand_local.pid = os.getpid()
    return rng.getrandbits(128).to_bytes(16, "little")


class BaseID(str):
    """An ID is just an interned hex string subclass (cheap, picklable, hashable)."""

    __slots__ = ()

    @classmethod
    def generate(cls) -> "BaseID":
        return cls(binascii.hexlify(_rand16()).decode())

    @classmethod
    def nil(cls) -> "BaseID":
        return cls("0" * 32)

    def is_nil(self) -> bool:
        return self == "0" * 32

    def hex(self) -> str:  # parity with ray's ObjectID.hex()
        return str(self)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({str.__repr__(self)})"


class ObjectID(BaseID):
    __slots__ = ()


class TaskID(BaseID):
    __slots__ = ()


class ActorID(BaseID):
    __slots__ = ()


class NodeID(BaseID):
    __slots__ = ()


class WorkerID(BaseID):
    __slots__ = ()


class PlacementGroupID(BaseID):
    __slots__ = ()


class JobID(BaseID):
    __slots__ = ()
