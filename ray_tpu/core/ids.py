"""Unique identifiers for the distributed futures core.

Equivalent in role to the reference's ID types (ray: src/ray/common/id.h) but
deliberately simple: 16 random bytes rendered as hex. IDs are value objects used
as dict keys throughout the control plane.
"""
from __future__ import annotations

import os
import binascii


class BaseID(str):
    """An ID is just an interned hex string subclass (cheap, picklable, hashable)."""

    __slots__ = ()

    @classmethod
    def generate(cls) -> "BaseID":
        return cls(binascii.hexlify(os.urandom(16)).decode())

    @classmethod
    def nil(cls) -> "BaseID":
        return cls("0" * 32)

    def is_nil(self) -> bool:
        return self == "0" * 32

    def hex(self) -> str:  # parity with ray's ObjectID.hex()
        return str(self)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({str.__repr__(self)})"


class ObjectID(BaseID):
    __slots__ = ()


class TaskID(BaseID):
    __slots__ = ()


class ActorID(BaseID):
    __slots__ = ()


class NodeID(BaseID):
    __slots__ = ()


class WorkerID(BaseID):
    __slots__ = ()


class PlacementGroupID(BaseID):
    __slots__ = ()


class JobID(BaseID):
    __slots__ = ()
