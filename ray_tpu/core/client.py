"""Synchronous client over the asyncio control-plane connection.

Both the driver and every worker process embed one of these — the analog of
the reference's CoreWorker library (ray: src/ray/core_worker/core_worker.h:292)
being linked into driver and worker processes alike. A dedicated thread runs
the asyncio loop; public methods are thread-safe and synchronous.

Fault tolerance: with ``reconnect=True`` the client survives a controller
bounce (reference: the GCS client's reconnection on NotifyGCSRestart,
gcs_rpc_client reconnect window). A request that fails on a dropped
connection re-dials with capped exponential backoff until
``RTPU_RECONNECT_MAX_S`` passes, then raises ConnectionError cleanly. On a
successful reconnect the owner's ``on_reconnect`` hook runs first (it
re-registers identity / re-reports state on the NEW connection) and the
client replays its pubsub subscriptions.
"""
from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, Awaitable, Callable, Dict, Optional

from ray_tpu import flags

from . import protocol

_BACKOFF_CAP_S = 2.0

# Transport-level timeout classes (asyncio.wait_for / Future.result). Exact
# types only — application errors like controller.GetTimeoutError subclass
# builtin TimeoutError and must surface to the caller, never retry.
import concurrent.futures as _cf  # noqa: E402

_TRANSPORT_TIMEOUTS = (asyncio.TimeoutError, _cf.TimeoutError, TimeoutError)


def _is_transport_timeout(e: BaseException) -> bool:
    return type(e) in _TRANSPORT_TIMEOUTS


class EventLoopThread:
    def __init__(self, name: str = "rtpu-io"):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._run, name=name, daemon=True)
        self.thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def call(self, coro: Awaitable[Any], timeout: Optional[float] = None) -> Any:
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_nowait(self, coro: Awaitable[Any]) -> "asyncio.Future":
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self) -> None:
        def _drain() -> None:
            for t in asyncio.all_tasks(self.loop):
                t.cancel()
            self.loop.call_soon(self.loop.stop)

        try:
            self.loop.call_soon_threadsafe(_drain)
        except RuntimeError:
            return
        self.thread.join(timeout=2)


class CoreClient:
    """Thread-safe request/push client to the controller."""

    def __init__(
        self,
        host: str,
        port: int,
        handler: Optional[Callable[[protocol.Connection, Dict[str, Any]], Awaitable[Any]]] = None,
        loop_thread: Optional[EventLoopThread] = None,
        reconnect: bool = False,
        on_reconnect: Optional[Callable[["CoreClient"], None]] = None,
    ):
        self.io = loop_thread or EventLoopThread()
        self.host = host
        self.port = port
        self.handler = handler
        self.reconnect_enabled = reconnect
        # Called (on the reconnecting thread) after a NEW connection is up,
        # before any retried request goes out: re-register identity,
        # re-report held state. Exceptions here fail the reconnect attempt.
        self.on_reconnect = on_reconnect
        self._closed = False
        # RLock: on_reconnect re-enters request()/ensure_connected() while
        # re-registering on the fresh connection.
        self._reconnect_lock = threading.RLock()
        self._subscriptions: set = set()
        # Stable identity for caches keyed per-connection (id() of a freed
        # client can be reused by a new one after shutdown/re-init).
        import secrets

        self.token = secrets.token_hex(8)
        self.conn: protocol.Connection = self._connect_once()

    def _connect_once(self) -> protocol.Connection:
        return self.io.call(
            protocol.connect(self.host, self.port, self.handler,
                             name=f"client->{self.host}:{self.port}"),
            timeout=10,
        )

    # ------------------------------------------------------------- reconnect

    def ensure_connected(self) -> None:
        """Re-dial a dropped connection with capped exponential backoff.

        No-op while the current connection is live. Raises ConnectionError
        once ``RTPU_RECONNECT_MAX_S`` passes without a successful dial —
        a permanently dead controller fails callers cleanly instead of
        hanging them forever.
        """
        if self._closed:
            raise ConnectionError("client is closed")
        if not self.conn.closed.is_set():
            return
        with self._reconnect_lock:
            if self._closed:
                raise ConnectionError("client is closed")
            if not self.conn.closed.is_set():
                return  # another thread already reconnected
            if not self.reconnect_enabled:
                raise ConnectionError(
                    f"connection to {self.host}:{self.port} is closed")
            max_s = flags.get("RTPU_RECONNECT_MAX_S")
            deadline = time.monotonic() + max_s
            backoff = flags.get("RTPU_RECONNECT_BACKOFF_S")

            def _pause(e: BaseException) -> None:
                now = time.monotonic()
                if now >= deadline or self._closed:
                    raise ConnectionError(
                        f"controller at {self.host}:{self.port} "
                        f"unreachable after {max_s:.0f}s "
                        f"({type(e).__name__}: {e})") from None
                time.sleep(min(backoff, max(0.0, deadline - now)))

            while True:
                try:
                    conn = self._connect_once()
                except Exception as e:
                    _pause(e)
                    backoff = min(backoff * 2, _BACKOFF_CAP_S)
                    continue
                self.conn = conn
                try:
                    # Identity first (register/re-report), then
                    # subscriptions: the hook is what makes the restarted
                    # controller recognize us.
                    if self.on_reconnect is not None:
                        self.on_reconnect(self)
                    for channel in sorted(self._subscriptions):
                        try:
                            self.io.call(self.conn.request(
                                {"kind": "subscribe", "channel": channel}),
                                timeout=10)
                        except Exception:
                            pass
                    return
                except (ConnectionError, asyncio.TimeoutError,
                        _cf.TimeoutError) as e:
                    # The FRESH connection died mid-handshake (controller
                    # bounced again under us) or the handshake timed out
                    # (still partitioned). Not fatal: keep dialing until
                    # the deadline.
                    try:
                        self.io.call_nowait(conn.close())
                    except Exception:
                        pass
                    _pause(e)
                    backoff = min(backoff * 2, _BACKOFF_CAP_S)

    def request(self, msg: Dict[str, Any], timeout: Optional[float] = None) -> Any:
        if msg.get("kind") == "subscribe" and msg.get("channel"):
            self._subscriptions.add(msg["channel"])
        # Per-call timeout with capped exponential backoff (partition
        # hardening, RTPU_RPC_TIMEOUT_S): an open-but-blackholed connection
        # never answers, so an unbounded request would hang forever. When
        # the flag is set AND the caller imposed no timeout of its own, each
        # attempt is bounded; a timed-out attempt treats the connection as
        # suspect — close, re-dial, re-send — with the attempt window
        # doubling (capped) so a slow-but-healthy controller isn't hammered.
        # Safe for blind re-sends: the controller's submit/create handlers
        # are idempotent by task/actor id. 0 (default) keeps the old
        # wait-forever behavior.
        rpc_t = 0.0
        if timeout is None and self.reconnect_enabled and not self._closed:
            try:
                rpc_t = float(flags.get("RTPU_RPC_TIMEOUT_S") or 0.0)
            except Exception:
                rpc_t = 0.0
        attempt_t = rpc_t or None
        retry_deadline: Optional[float] = None
        while True:
            try:
                return self.io.call(
                    self.conn.request(msg, timeout if not rpc_t
                                      else attempt_t),
                    timeout=None)
            except (ConnectionError, asyncio.TimeoutError,
                    _cf.TimeoutError) as e:
                timed_out = _is_transport_timeout(e)
                if not timed_out and not isinstance(e, ConnectionError):
                    raise  # app-level timeout subclass (GetTimeoutError)
                if timed_out and not rpc_t:
                    raise  # the caller's own timeout: surface it unchanged
                if self._closed or not self.reconnect_enabled:
                    raise
                # One retry window across flapping reconnects: each
                # ensure_connected has its own backoff deadline, but a
                # connection that dies between reconnect and retry must not
                # extend the overall budget forever.
                if retry_deadline is None:
                    retry_deadline = (time.monotonic()
                                      + flags.get("RTPU_RECONNECT_MAX_S"))
                elif time.monotonic() >= retry_deadline:
                    raise
                if timed_out:
                    # Suspect connection (open but silent): force a fresh
                    # dial; the re-send below goes out on the new one.
                    try:
                        self.io.call(self.conn.close(), timeout=2)
                    except Exception:
                        pass
                    attempt_t = min((attempt_t or rpc_t) * 2,
                                    max(rpc_t * 8, 10.0))
                try:
                    self.ensure_connected()
                except (asyncio.TimeoutError, _cf.TimeoutError) as e2:
                    # The reconnect handshake itself timed out (still
                    # partitioned): keep retrying inside the window.
                    if time.monotonic() >= retry_deadline:
                        raise ConnectionError(
                            f"controller handshake kept timing out "
                            f"({e2!r})") from e2
                    time.sleep(min(0.2, rpc_t or 0.2))

    def request_async(self, msg: Dict[str, Any]) -> "asyncio.Future":
        return self.io.call_nowait(self.conn.request(msg))

    def send(self, msg: Dict[str, Any]) -> None:
        self.io.call(self.conn.send(msg))

    def send_nowait(self, msg: Dict[str, Any]) -> None:
        """Fire-and-forget without blocking the calling thread (hot-path
        reports like direct-dispatch task_done)."""
        self.io.call_nowait(self.conn.send(msg))

    def close(self) -> None:
        self._closed = True
        try:
            self.io.call(self.conn.close(), timeout=2)
        except Exception:
            pass
        self.io.stop()
