"""Synchronous client over the asyncio control-plane connection.

Both the driver and every worker process embed one of these — the analog of
the reference's CoreWorker library (ray: src/ray/core_worker/core_worker.h:292)
being linked into driver and worker processes alike. A dedicated thread runs
the asyncio loop; public methods are thread-safe and synchronous.
"""
from __future__ import annotations

import asyncio
import threading
from typing import Any, Awaitable, Callable, Dict, Optional

from . import protocol


class EventLoopThread:
    def __init__(self, name: str = "rtpu-io"):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._run, name=name, daemon=True)
        self.thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def call(self, coro: Awaitable[Any], timeout: Optional[float] = None) -> Any:
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_nowait(self, coro: Awaitable[Any]) -> "asyncio.Future":
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self) -> None:
        def _drain() -> None:
            for t in asyncio.all_tasks(self.loop):
                t.cancel()
            self.loop.call_soon(self.loop.stop)

        try:
            self.loop.call_soon_threadsafe(_drain)
        except RuntimeError:
            return
        self.thread.join(timeout=2)


class CoreClient:
    """Thread-safe request/push client to the controller."""

    def __init__(
        self,
        host: str,
        port: int,
        handler: Optional[Callable[[protocol.Connection, Dict[str, Any]], Awaitable[Any]]] = None,
        loop_thread: Optional[EventLoopThread] = None,
    ):
        self.io = loop_thread or EventLoopThread()
        self.host = host
        self.port = port
        # Stable identity for caches keyed per-connection (id() of a freed
        # client can be reused by a new one after shutdown/re-init).
        import secrets

        self.token = secrets.token_hex(8)
        self.conn: protocol.Connection = self.io.call(
            protocol.connect(host, port, handler, name=f"client->{host}:{port}"), timeout=10
        )

    def request(self, msg: Dict[str, Any], timeout: Optional[float] = None) -> Any:
        return self.io.call(self.conn.request(msg, timeout), timeout=None)

    def request_async(self, msg: Dict[str, Any]) -> "asyncio.Future":
        return self.io.call_nowait(self.conn.request(msg))

    def send(self, msg: Dict[str, Any]) -> None:
        self.io.call(self.conn.send(msg))

    def send_nowait(self, msg: Dict[str, Any]) -> None:
        """Fire-and-forget without blocking the calling thread (hot-path
        reports like direct-dispatch task_done)."""
        self.io.call_nowait(self.conn.send(msg))

    def close(self) -> None:
        try:
            self.io.call(self.conn.close(), timeout=2)
        except Exception:
            pass
        self.io.stop()
